#!/usr/bin/env python
"""Benchmark: training throughput + MFU for the reference's headline models.

The reference's published numbers (reference pytorch/README.md:41-43,122-125,
128): PyramidNet-110 alpha=270, CIFAR-10, batch 64, Tesla P100 — 0.255 s/batch
= 251 samples/sec on one GPU.  This script times the same training step on
whatever device JAX exposes, plus the BASELINE.json north-star workload
(ResNet-50, ImageNet shapes), across a batch-size sweep, and computes MFU
from the compiled step's `cost_analysis()` FLOPs against the detected chip's
bf16 peak.

stdout carries exactly ONE JSON line (the driver contract), kept COMPACT —
round 4's line grew past the driver's tail-capture window and truncated
mid-record (BENCH_r04.json parsed:null), so the headline numbers had no
machine-readable artifact.  The final line now carries only scalars:

    {"metric": "...", "value": N, "unit": "samples/sec", "vs_baseline": N,
     "mfu": N, "resnet50_mfu": N, "lm_mfu": N, "lm_tokens_per_sec": N,
     "records_file": "bench_records.json"}

The full per-config records and the modeled scaling section are written to
``records_file`` (JSON) and echoed to stderr.  vs_baseline > 1.0 means
faster than the reference's single-P100 batch time.  Everything
human-readable (the per-config table, the reference-table comparison) also
goes to stderr.

Honest timing: warmup steps first (compile + autotune), then blocking timing
of a fixed sample budget with data already on device.  A VALUE FETCH ends the
timed region, not block_until_ready: on the tunneled TPU backend here,
block_until_ready returns before device execution finishes (verified: a
50-step chain "completed" in 77 ms, then fetching the losses took 41 s).
float() forces the whole dependency chain; one scalar round-trip amortized
over the whole timed run.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import tempfile
import time
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np
import optax

BASELINE_SAMPLES_PER_SEC = 64 / 0.255  # reference pytorch/README.md:41 (P100)

# chip peaks + analytic LM FLOPs live in the obs subsystem now (PR 3);
# bench.py re-exports the old names so scripts/lm_sweep.py et al. keep
# importing `from bench import lm_analytic_flops, peak_flops_per_chip`
from dtdl_tpu.obs.goodput import (  # noqa: E402
    _PEAK_BF16, lm_train_flops, peak_flops_per_chip,
)

lm_analytic_flops = lm_train_flops


def _flops_of(compiled) -> float | None:
    """Total FLOPs of one compiled step, from XLA's cost analysis."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    f = ca.get("flops")
    return float(f) if f else None


def bench_one(model_name: str, batch_size: int, warmup: int = 10,
              sample_budget: int | None = None) -> dict:
    """Time one (model, batch_size) config; returns the record row."""
    from dtdl_tpu.models import pyramidnet, resnet50
    from dtdl_tpu.parallel import choose_strategy
    from dtdl_tpu.train import init_state, make_train_step

    strategy = choose_strategy("auto")
    if model_name == "resnet50":
        model = resnet50(dtype=jnp.bfloat16, s2d_stem=True)
        shape, classes = (224, 224, 3), 1000
        sample_budget = sample_budget or 4096
    else:
        model = pyramidnet(dtype=jnp.bfloat16)
        shape, classes = (32, 32, 3), 10
        sample_budget = sample_budget or 9600
    iters = max(20, sample_budget // batch_size)

    tx = optax.sgd(0.1, momentum=0.9, nesterov=False)
    state = strategy.replicate(init_state(
        model, jax.random.PRNGKey(0), jnp.zeros((1,) + shape), tx))
    step = make_train_step(strategy)

    rng = np.random.default_rng(0)
    # a handful of distinct on-device batches so no lucky caching occurs
    batches = [strategy.shard_batch({
        "image": jnp.asarray(rng.normal(size=(batch_size,) + shape),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, classes, batch_size)),
    }) for _ in range(4)]

    compiled = step.lower(state, batches[0]).compile()
    flops_per_step = _flops_of(compiled)

    for i in range(warmup):
        state, metrics = compiled(state, batches[i % len(batches)])
    float(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics = compiled(state, batches[i % len(batches)])
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    samples_per_sec = batch_size * iters / dt
    row = {
        "model": model_name,
        "batch_size": batch_size,
        "samples_per_sec": round(samples_per_sec, 2),
        "step_time_ms": round(1e3 * dt / iters, 3),
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
    }
    peak = peak_flops_per_chip()
    if flops_per_step:
        # cost_analysis() reports the per-device (SPMD-partitioned) module's
        # FLOPs, so the denominator is the per-chip peak — not peak * n_chips
        achieved = flops_per_step * iters / dt
        row["flops_per_step"] = flops_per_step
        row["achieved_tflops"] = round(achieved / 1e12, 2)
        if peak:
            row["mfu"] = round(achieved / peak, 4)
    return row


def bench_lm(batch_size: int = 8, seq: int = 4096, size: str = "base",
             warmup: int = 5, iters: int = 30) -> dict:
    """Causal-LM train step (TransformerLM, Pallas flash attention, bf16)
    — the long-context workload (same configs as the README's tokens/sec
    table).  Reports tokens/sec + MFU.

    'large' (d_model 1024, 239M params) is the roofline-cash row
    (LM_ROOFLINE.md §5: "further MFU comes from model shape").  Its bench
    config was swept on the v5e (LM_ROOFLINE.md §6): **bs 4, no remat,
    dense head** wins at 0.583 MFU — at bs 4 the activations (~7 GB) and
    the [4, 4095, 32k] f32 logits (~2.1 GB) fit beside the AdamW state,
    and both remat (+1x fwd recompute) and the chunked head (backward
    re-does the logit matmuls) burn real FLOPs the analytic MFU numerator
    deliberately does not credit (remat'd bs8 = 0.419, chunked bs4 =
    0.560).  The preset
    keeps ``remat=True`` as the safe default for *user* workloads at
    bigger batch; the bench overrides it because the measurement exists
    to show what the hardware ceiling allows.

    ``mfu`` uses the analytic model-FLOP count (`lm_analytic_flops`);
    ``mfu_xla`` keeps the raw cost_analysis number, which understates the
    step because Pallas kernel FLOPs are invisible to it."""
    import optax as _optax
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.parallel import choose_strategy
    from dtdl_tpu.train import init_state, make_lm_train_step

    strategy = choose_strategy("auto")
    overrides = {"remat": False} if size == "large" else {}
    model = transformer_lm(size, max_seq=seq, **overrides)
    tx = _optax.adamw(3e-4)
    state = strategy.replicate(init_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((1, seq), jnp.int32), tx))
    step = make_lm_train_step(strategy)
    rng = np.random.default_rng(0)
    batches = [strategy.shard_batch({
        "tokens": jnp.asarray(
            rng.integers(0, model.vocab_size, (batch_size, seq)), jnp.int32),
    }) for _ in range(4)]
    compiled = step.lower(state, batches[0]).compile()
    xla_flops = _flops_of(compiled)
    flops_per_step = lm_analytic_flops(model, batch_size, seq)

    for i in range(warmup):
        state, metrics = compiled(state, batches[i % len(batches)])
    float(metrics["loss"])
    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics = compiled(state, batches[i % len(batches)])
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite LM loss {final_loss}"

    tokens_per_sec = batch_size * (seq - 1) * iters / dt
    row = {
        "model": "lm",
        "size": size,
        "batch_size": batch_size,
        "seq": seq,
        "tokens_per_sec": round(tokens_per_sec, 0),
        "samples_per_sec": round(batch_size * iters / dt, 2),
        "step_time_ms": round(1e3 * dt / iters, 3),
        "flops_per_step": flops_per_step,
        "flops_source": "analytic",
        "achieved_tflops": round(flops_per_step * iters / dt / 1e12, 2),
    }
    peak = peak_flops_per_chip()
    if peak:
        row["mfu"] = round(flops_per_step * iters / dt / peak, 4)
        if xla_flops:
            row["mfu_xla"] = round(xla_flops * iters / dt / peak, 4)
    return row


def bench_host_overhead(steps: int = 192, batch_size: int = 64,
                        unroll: int = 8, log_interval: int = 24) -> dict:
    """Host-overhead microbench: sync-every-step vs async-drain vs unrolled.

    Drives the SAME ``train_epoch`` loop three ways over an identical
    synthetic dataset with a deliberately tiny model (2x64-unit MLP), so
    the device step is far below the host's per-step work and the loop
    overhead — per-step ``float()`` syncs vs boundary drains vs one
    dispatch per ``unroll`` steps — dominates what's measured.  This is the
    async-dispatch-discipline receipt (SCALING.md): the deltas here are
    pure host↔device pipeline stalls, the cost every sub-ms-step TPU
    workload pays when a loop reads a metric on the step it just
    dispatched.
    """
    from dtdl_tpu.data.loader import DataLoader
    from dtdl_tpu.models import MLP
    from dtdl_tpu.parallel.strategy import SingleDevice
    from dtdl_tpu.train import init_state, make_train_step, train_epoch

    strategy = SingleDevice()
    rng = np.random.default_rng(0)
    n = steps * batch_size
    x = rng.normal(size=(n, 64)).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int64)
    loader = DataLoader({"image": x, "label": y}, batch_size, shuffle=False)
    tx = optax.sgd(0.01)
    step = make_train_step(strategy)

    def fresh_state():
        return strategy.replicate(init_state(
            MLP(n_units=64), jax.random.PRNGKey(0),
            jnp.zeros((1, 64)), tx))

    modes = {
        "sync": dict(sync_every_step=True),
        "async": dict(),
        f"unroll{unroll}": dict(unroll=unroll),
    }
    row = {"model": "host_overhead", "batch_size": batch_size,
           "steps": steps, "log_interval": log_interval, "unroll": unroll}
    rates = {}
    for name, kw in modes.items():
        state = fresh_state()
        # epoch 0 = warmup (compile); epoch 1 = timed
        state, _ = train_epoch(step, state, loader, strategy,
                               log_interval=log_interval, **kw)
        t0 = time.perf_counter()
        state, means = train_epoch(step, state, loader, strategy,
                                   log_interval=log_interval, **kw)
        dt = time.perf_counter() - t0
        assert np.isfinite(means["loss"])
        rates[name] = steps / dt
        row[f"{name}_steps_per_sec"] = round(steps / dt, 1)
    row["async_speedup_vs_sync"] = round(rates["async"] / rates["sync"], 3)
    row[f"unroll{unroll}_speedup_vs_sync"] = round(
        rates[f"unroll{unroll}"] / rates["sync"], 3)
    return row


def bench_observability(steps: int = 192, batch_size: int = 64,
                        log_interval: int = 24) -> dict:
    """Observability overhead receipt: the SAME async ``train_epoch``
    with the obs layer off vs fully on (tracer + recompile sentinel +
    goodput meter).

    Uses the host-overhead harness's deliberately tiny model so the
    host-side loop dominates — the worst case for per-step span/sentinel
    bookkeeping.  The contract (ISSUE 3): ``overhead_frac`` (1 -
    on/off steps/sec) stays under 2%; anything more means a span or
    sentinel snuck device work or allocation into the hot path.
    """
    from dtdl_tpu.data.loader import DataLoader
    from dtdl_tpu.models import MLP
    from dtdl_tpu.obs import GoodputMeter, Observer
    from dtdl_tpu.parallel.strategy import SingleDevice
    from dtdl_tpu.train import init_state, make_train_step, train_epoch

    strategy = SingleDevice()
    rng = np.random.default_rng(0)
    n = steps * batch_size
    x = rng.normal(size=(n, 64)).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int64)
    loader = DataLoader({"image": x, "label": y}, batch_size, shuffle=False)
    tx = optax.sgd(0.01)
    step = make_train_step(strategy)

    def fresh_state():
        return strategy.replicate(init_state(
            MLP(n_units=64), jax.random.PRNGKey(0),
            jnp.zeros((1, 64)), tx))

    def run(observer):
        state = fresh_state()
        # epoch 0 = warmup (compile); epoch 1 = timed
        state, _ = train_epoch(step, state, loader, strategy,
                               log_interval=log_interval,
                               observer=observer)
        if observer is not None:
            # drop the warmup windows: the compile stall would otherwise
            # BE the reported step-time p99
            from dtdl_tpu.obs import LogHistogram
            observer.step_time_s = LogHistogram()
        t0 = time.perf_counter()
        state, means = train_epoch(step, state, loader, strategy,
                                   log_interval=log_interval,
                                   observer=observer)
        dt = time.perf_counter() - t0
        assert np.isfinite(means["loss"])
        return steps / dt

    off = run(None)
    obs = Observer(trace=True, sentinel="warn",
                   goodput=GoodputMeter(samples_per_step=batch_size))
    on = run(obs)
    return {"model": "observability", "batch_size": batch_size,
            "steps": steps, "log_interval": log_interval,
            "off_steps_per_sec": round(off, 1),
            "on_steps_per_sec": round(on, 1),
            "overhead_frac": round(1.0 - on / off, 4),
            "trace_events": len(obs.tracer),
            "recompile_events": len(obs.sentinel.events),
            "step_time_p99_ms": round(
                obs.step_time_s.p99 * 1e3, 3)}


def bench_robustness(steps: int = 48, batch_size: int = 256,
                     log_interval: int = 12) -> dict:
    """Guard-overhead receipt: the SAME async ``train_epoch`` with the
    resil step guard off vs folded into the compiled step (policy=skip,
    host observe at every drain).

    The guard's in-jit cost — one global grad norm + a scalar-predicated
    state select — is DEVICE work that scales with parameter count but
    not batch, so (unlike the host-overhead/observability rows, whose
    additions are host-side constants) a sub-ms toy step would inflate
    the ratio far beyond anything a real workload sees.  The row
    therefore uses a wider MLP at a step time in the low milliseconds —
    the small end of real training steps; on anything larger the
    fraction only shrinks, since the guard cost is ~O(params) against
    O(params x batch) compute.  The contract (ISSUE 5, same bar as the
    observer): ``overhead_frac`` stays under 2%.  ``guard_bad_steps``
    must be 0 — a fault-free run proves the guard never fires
    spuriously.
    """
    from dtdl_tpu.data.loader import DataLoader
    from dtdl_tpu.models import MLP
    from dtdl_tpu.parallel.strategy import SingleDevice
    from dtdl_tpu.resil import StepGuard
    from dtdl_tpu.train import init_state, make_train_step, train_epoch

    strategy = SingleDevice()
    rng = np.random.default_rng(0)
    n = steps * batch_size
    dim = 256
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int64)
    loader = DataLoader({"image": x, "label": y}, batch_size, shuffle=False)
    tx = optax.sgd(0.01)

    def fresh_state():
        return strategy.replicate(init_state(
            MLP(n_units=512), jax.random.PRNGKey(0),
            jnp.zeros((1, dim)), tx))

    guard = StepGuard(policy="skip")
    modes = {"off": (make_train_step(strategy), None),
             "on": (make_train_step(strategy, guard=guard), guard)}
    states = {k: fresh_state() for k in modes}
    best = {k: 0.0 for k in modes}

    def one_epoch(name):
        step, g = modes[name]
        t0 = time.perf_counter()
        states[name], means = train_epoch(
            step, states[name], loader, strategy,
            log_interval=log_interval, guard=g)
        dt = time.perf_counter() - t0
        assert np.isfinite(means["loss"])
        return steps / dt

    # warmup epoch each (compile), then interleaved repetitions with
    # best-of-N per mode: a ~1% delta is far below this box's run-to-run
    # drift (whole epochs swing 20%+ under ambient load), and load noise
    # is additive-positive — the best epoch of many alternating reps
    # approaches each mode's true floor instead of attributing ambient
    # drift to whichever mode ran second
    for name in modes:
        one_epoch(name)
    for _ in range(6):
        for name in modes:
            best[name] = max(best[name], one_epoch(name))
    return {"model": "robustness", "batch_size": batch_size,
            "steps": steps, "log_interval": log_interval,
            "off_steps_per_sec": round(best["off"], 1),
            "on_steps_per_sec": round(best["on"], 1),
            "overhead_frac": round(1.0 - best["on"] / best["off"], 4),
            **guard.summary()}


def bench_audit() -> dict:
    """Program-shape receipt (ISSUE 15): the pinned-program audit as a
    bench row, so the trajectory files capture drift the way they
    capture throughput.  Per program: collective counts + bytes (jaxpr
    AND compiled HLO), host transfers/callbacks, and donated bytes —
    plus the named drift list against the checked-in baseline
    (dtdl_tpu/analysis/baselines.json; empty = the program shapes are
    exactly what the last intentional rebase recorded)."""
    from dtdl_tpu.analysis import contracts

    runnable, skipped = contracts.runnable_programs()
    reports = contracts.audit_programs(runnable)
    drift = contracts.compare_to_baseline(reports,
                                          contracts.load_baseline())
    row = {"model": "audit",
           "drift": [f.render() for f in drift],
           "drift_findings": len(drift),
           # geometries this process's device count cannot build (the
           # megatron step needs 8) — audited in the test harness's
           # forced 8-device platform instead of silently erroring here
           "skipped": skipped}
    for name, rep in sorted(reports.items()):
        row[name] = {
            "collectives_hlo": {k: v["count"] for k, v in
                                rep["hlo_collectives"].items()},
            "collective_bytes_hlo": sum(
                v["bytes"] for v in rep["hlo_collectives"].values()),
            "collectives_jaxpr": {k: v["count"] for k, v in
                                  rep["jaxpr_collectives"].items()},
            "host_transfers": rep["host_transfers"],
            "callbacks": rep["callbacks"],
            "donated_bytes": rep["donated_bytes"],
            "donated_args": f"{rep['n_donated_args']}/"
                            f"{rep['n_expected_donated']}",
        }
    return row


def bench_kernels(head_dims=(64, 128), seqs=(4096,), iters: int = 2,
                  warmup: int = 1, vocabs=(32768, 256),
                  samp_batch: int = 8, samp_iters: int = 20) -> dict:
    """Kernel-round microbench (round 13): old vs new hot-path kernels.

    **Attention** — fwd+bwd flash attention at B=1/H=1, bf16, causal,
    per (head_dim, seq): the round-12 configuration (standalone
    ``apply_rope`` + the old hardcoded 1024×1024 blocks) against the
    round-13 one (rope fused into the kernels + autotune-table blocks).
    Throughput is USEFUL FLOPs (the goodput convention: causal at the
    computed half, backward at 2x forward, recompute and rope never
    credited) so old and new divide identical numerators.

    **Sampling** — the serve decode epilogue per vocab size: scale +
    top-k + top-p filter + categorical draw over [B, V] logits, sorted
    (descending argsort + cumsum + inverse argsort — the round-12 path,
    kept as ``filter_logits_sorted``) vs sortless (32-round threshold
    bisection — ``filter_logits``).

    Honesty: on CPU the attention kernels run under the Pallas
    interpreter (``interpret: true`` in the row) — block geometry and
    arithmetic are exactly the TPU program, but relative timings mix in
    interpreter overheads, and the rope-fusion HBM win by construction
    cannot show up where there is no HBM (goodput.lm_rope_hbm_bytes
    carries the bytes arithmetic; LM_ROOFLINE.md the expected v5e
    effect).  Default seqs stay short for the same reason — pass
    ``--kernel-seqs 4096,32768`` on a real chip.
    """
    from dtdl_tpu.obs.goodput import lm_rope_hbm_bytes
    from dtdl_tpu.ops.attention import flash_attention, resolve_blocks
    from dtdl_tpu.ops.rope import apply_rope, rope_frequencies
    from dtdl_tpu.serve.sampling import filter_logits, filter_logits_sorted

    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)

    def timed(fn, *args):
        fn_j = jax.jit(fn)
        for _ in range(warmup):
            out = fn_j(*args)
        float(jax.tree.leaves(out)[0].ravel()[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn_j(*args)
        float(jax.tree.leaves(out)[0].ravel()[0])
        return (time.perf_counter() - t0) / iters

    attn = []
    for d in head_dims:
        cos, sin = rope_frequencies(d, max(seqs))
        for s in seqs:
            q, k, v = (jnp.asarray(rng.normal(size=(1, 1, s, d)),
                                   jnp.bfloat16) for _ in range(3))

            def loss_old(q, k, v):
                qr = apply_rope(q, cos[:s], sin[:s])
                kr = apply_rope(k, cos[:s], sin[:s])
                o = flash_attention(qr, kr, v, causal=True,
                                    block_q=1024, block_k=1024)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def loss_new(q, k, v):
                o = flash_attention(q, k, v, causal=True,
                                    rope=(cos, sin))
                return jnp.sum(o.astype(jnp.float32) ** 2)

            old_s = timed(jax.grad(loss_old, (0, 1, 2)), q, k, v)
            new_s = timed(jax.grad(loss_new, (0, 1, 2)), q, k, v)
            useful = 3 * 2 * 1 * 1 * float(s) * float(s) * d  # fwd+2x bwd
            attn.append({
                "head_dim": d, "seq": s,
                "blocks": list(resolve_blocks(d, s)),
                "old_ms": round(old_s * 1e3, 2),
                "new_ms": round(new_s * 1e3, 2),
                "old_tflops": round(useful / old_s / 1e12, 4),
                "new_tflops": round(useful / new_s / 1e12, 4),
                "speedup": round(old_s / new_s, 3),
                # the HBM traffic the fusion removes at THIS geometry
                # (one layer, B=1/H=1) — the quantity that, not the CPU
                # ms, is the v5e claim (LM_ROOFLINE.md round 13)
                "rope_bytes_saved": int(lm_rope_hbm_bytes(
                    type("C", (), {"n_layers": 1, "n_heads": 1,
                                   "head_dim": d})(), 1, s)),
            })

    samp = []
    for v_sz in vocabs:
        logits = jnp.asarray(rng.normal(size=(samp_batch, v_sz)) * 3,
                             jnp.float32)
        temp = jnp.full((samp_batch,), 0.8, jnp.float32)
        top_k = jnp.full((samp_batch,), 50, jnp.int32)
        top_p = jnp.full((samp_batch,), 0.9, jnp.float32)
        key = jax.random.PRNGKey(0)

        def draw(filt):
            def fn(lg):
                masked = filt(lg, temp, top_k, top_p)
                return jax.random.categorical(key, masked, axis=-1)
            return fn

        sort_s = timed(draw(filter_logits_sorted), logits)
        less_s = timed(draw(filter_logits), logits)
        samp.append({
            "vocab": v_sz, "batch": samp_batch,
            "sorted_us": round(sort_s * 1e6, 1),
            "sortless_us": round(less_s * 1e6, 1),
            "speedup": round(sort_s / less_s, 3),
        })

    return {"model": "kernels", "interpret": interpret,
            "iters": iters, "attention": attn, "sampling": samp}


def bench_serving(size: str = None, slot_sweep=(1, 4, 8),
                  new_tokens: int = 32) -> dict:
    """Serving throughput: prefill vs decode tokens/sec vs batch size.

    Drives the dtdl_tpu.serve engine directly (no scheduler policy in the
    timed region): for each slot count B, prefill B prompts of one bucket
    and run ``new_tokens`` batched decode steps.  The two phases are timed
    separately because they sit on opposite ends of the roofline — prefill
    is one matmul-heavy pass over the whole prompt (compute-bound), decode
    re-reads every weight once per token (HBM-bandwidth-bound), which is
    why decode tokens/sec should scale near-linearly with B until the KV
    reads catch up with the weight reads (SCALING.md "Serving latency
    model").  Value fetch ends each timed region, per the module contract.
    """
    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.serve import InferenceEngine

    if size is None:
        size = "tiny" if jax.devices()[0].platform == "cpu" else "base"
    model = transformer_lm(size, attn_impl="dense", dtype=jnp.float32)
    prompt_len = min(model.max_seq // 2, 512)
    new_tokens = min(new_tokens, model.max_seq - prompt_len)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    rng = np.random.default_rng(0)
    row = {"model": "serving", "size": size, "prompt_len": prompt_len,
           "new_tokens": new_tokens, "sweep": []}
    for B in slot_sweep:
        engine = InferenceEngine(model, params, n_slots=B,
                                 buckets=(prompt_len,))
        greedy = (jnp.zeros(B), jnp.zeros(B, jnp.int32), jnp.ones(B))
        key = jax.random.PRNGKey(0)
        prompts = [rng.integers(0, model.vocab_size, prompt_len)
                   for _ in range(B)]

        def fill(arena, last):
            for slot, p in enumerate(prompts):
                arena, last, _ = engine.prefill(arena, last, slot, p)
            return arena, last

        # warmup: compile prefill + decode once
        arena, last = fill(engine.init_arena(), engine.init_last_tokens())
        arena, last, _ = engine.decode(arena, last, np.ones(B, bool),
                                       key, *greedy)
        # timed prefill (fresh arena, same compiled program)
        arena, last = engine.init_arena(), engine.init_last_tokens()
        t0 = time.perf_counter()
        arena, last = fill(arena, last)
        np.asarray(last)
        dt_prefill = time.perf_counter() - t0
        # timed decode at full occupancy
        active = np.ones(B, bool)
        t0 = time.perf_counter()
        for _ in range(new_tokens):
            arena, last, _ = engine.decode(arena, last, active, key,
                                           *greedy)
        np.asarray(last)
        dt_decode = time.perf_counter() - t0
        row["sweep"].append({
            "batch_size": B,
            "prefill_tokens_per_sec": round(B * prompt_len / dt_prefill, 1),
            "decode_tokens_per_sec": round(B * new_tokens / dt_decode, 1),
            "decode_ms_per_token": round(
                1e3 * dt_decode / new_tokens, 3),
        })
    row["spec"] = bench_spec_decode(model, params)
    row["paged"] = bench_paged()
    row["quant"] = bench_quant(model, params)
    return row


class _ReplayDraft:
    """Perfect drafts replayed from a probe run's recorded sequences —
    the synthetic HIGH-ACCEPTANCE workload.  Greedy decode is
    deterministic, so replaying the probe's continuation drafts exactly
    what the model will say: acceptance ~1 and the sweep measures the
    verify path's mechanism ceiling (one param sweep -> k+1 tokens), the
    way the host-overhead row measures dispatch headroom.  A real
    workload lands between this and the k=0 baseline in proportion to
    its draft source's acceptance rate (SCALING.md "Speculative decoding
    arithmetic")."""

    def __init__(self, seqs):
        self.seqs = [list(s) for s in seqs]

    def propose(self, ctx, k):
        ctx = list(np.asarray(ctx, np.int32))
        for full in self.seqs:
            if ctx == full[:len(ctx)]:
                return np.asarray(full[len(ctx):len(ctx) + k], np.int32)
        return np.zeros((0,), np.int32)


def bench_spec_decode(model, params, n_slots: int = 4,
                      new_tokens: int = 96, ks=(0, 2, 4)) -> list:
    """Speculative-decoding sweep: scheduler-driven tokens/sec at draft
    widths k ∈ {0, 2, 4}, greedy and temperature sampling.

    Greedy rows draft from :class:`_ReplayDraft` (probe-run replay, the
    high-acceptance synthetic workload — see its docstring); temperature
    rows draft with the production n-gram source against near-uniform
    sampled content, the low-acceptance end (rejection sampling accepts
    a draft with probability p(draft), small at high entropy — the
    acceptance_rate field is the calibration).  k=0 is the plain
    continuous-batching baseline through the SAME scheduler, so the
    comparison isolates verify-vs-decode.  Each config runs once
    unmeasured to compile its programs, then re-runs timed.
    """
    from dtdl_tpu.serve import InferenceEngine, NGramDraft, Request, \
        SampleParams, Scheduler

    engine = InferenceEngine(model, params, n_slots=n_slots)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size, n).tolist()
               for n in rng.integers(8, 16, 2 * n_slots)]
    # probe: record each prompt's greedy continuation once (plain decode,
    # also the warmup for the prefill/decode programs)
    probes = [Request(p, new_tokens) for p in prompts]
    Scheduler(engine, harvest_lag=1).run(probes)
    replay = _ReplayDraft([list(r.prompt) + r.tokens for r in probes])
    out = []
    for k in ks:
        for temp in (0.0, 0.8):
            sp = SampleParams(temperature=temp,
                              top_p=0.95 if temp else 1.0)
            draft = replay if temp == 0.0 else NGramDraft()

            def run():
                reqs = [Request(p, new_tokens, sampling=sp, speculate=k)
                        for p in prompts]
                sched = Scheduler(engine, harvest_lag=1, draft=draft)
                sched.run(reqs)
                return sched.metrics.summary()

            run()                      # warmup: compile + caches
            s = run()                  # timed (wall between first admit
            out.append({               # and last harvest, per ServeMetrics)
                "k": k, "temperature": temp,
                "decode_tokens_per_sec": s["decode_tokens_per_sec"],
                "tokens_per_step": s["tokens_per_step_mean"],
                "acceptance_rate": s["spec_acceptance_rate"],
                "draft_s": s["draft_s"],
            })
    return out


def bench_paged(size: str = "small", n_slots: int = 4,
                page_size: int = 64, new_tokens: int = 8) -> list:
    """Paged-KV sweep: dense vs paged vs paged+prefix-cache on
    repeated-system-prompt traffic (ISSUE 6 acceptance).

    The traffic is the production shape the prefix cache exists for:
    every request shares a multi-page system prompt (3/4 of the
    context) and differs only in a short unique suffix.  Dense and
    prefix-off paged rows prefill the FULL prompt per request (through
    its big bucket); the prefix-cache row computes the shared pages
    once per run and maps them read-only into every later admission,
    so those admissions re-enter through the small SUFFIX bucket — the
    ttft_s_mean gap between the dense and prefix rows is the measured
    cache win, and prefix_hit_rate / prefill_tokens_saved are the
    receipts that the skip actually happened (the cache is
    per-Scheduler, so each timed run pays its own one cold prefill —
    no cross-run warm state flatters the row).  The traffic is ONE
    admission wave (n_requests == n_slots) so ttft_s_mean measures
    prefill, not queue wait behind decode, and the sweep uses the
    'small' model even on CPU — at 'tiny' scale the skipped prefill
    FLOPs drown in per-dispatch host overhead and the row measures
    nothing.  Decode throughput is its own field; on TPU it touches
    the same HBM bytes either way (pages are layout, not compute; on
    this CPU box the table gather shows up as a decode tax the
    roofline hides).  The paged win proper is capacity — slots per
    HBM byte — priced analytically in SCALING.md "Paged KV
    arithmetic".  The two paged rows share ONE engine (the prefix
    cache is scheduler policy), so the whole sweep compiles two
    program sets: dense and paged.
    """
    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.serve import InferenceEngine, Request, Scheduler

    model = transformer_lm(size, attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    rng = np.random.default_rng(0)
    n_sys = (3 * model.max_seq // 4) // page_size * page_size
    system = rng.integers(0, model.vocab_size, n_sys).tolist()
    new_tokens = min(new_tokens,
                     model.max_seq - n_sys - page_size)
    prompts = [system + rng.integers(0, model.vocab_size,
                                     int(n)).tolist()
               for n in rng.integers(page_size // 2, page_size,
                                     n_slots)]
    dense = InferenceEngine(model, params, n_slots=n_slots)
    paged = InferenceEngine(model, params, n_slots=n_slots,
                            page_size=page_size)
    out = []
    for label, engine, prefix in (("dense", dense, False),
                                  ("paged", paged, False),
                                  ("paged+prefix", paged, True)):

        def run():
            reqs = [Request(p, new_tokens) for p in prompts]
            sched = Scheduler(engine, harvest_lag=1,
                              prefix_cache=prefix)
            sched.run(reqs)
            return sched.metrics.summary()

        run()                      # warmup: compile full + suffix buckets
        s = run()                  # timed
        out.append({
            "arena": label,
            "page_size": page_size if engine.paged else 0,
            "decode_tokens_per_sec": s["decode_tokens_per_sec"],
            "ttft_s_mean": s["ttft_s_mean"],
            "prefix_hit_rate": s["prefix_hit_rate"],
            "prefill_tokens_saved": s["prefill_tokens_saved"],
            "pages_in_use_peak": s["pages_in_use_peak"],
        })
    return out


def bench_kv_hierarchy(size: str = "small", page_size: int = 64,
                       new_tokens: int = 8) -> dict:
    """Hierarchical KV cache row (round 23 acceptance).

    One shared-system-prompt request measured at every tier of the
    hierarchy: **cold** (full prefill, the price the cache avoids),
    **HBM hit** (the round-6 prefix cache: suffix-only prefill),
    **host hit** (the pages were evicted to the host-DRAM spill store
    and re-enter via the batched inject path), **disk hit** (host
    budget of one byte forces every spill through the checksummed
    mmap file).  The claim the row must carry: restore beats
    recompute — ``ttft_s_host_hit < ttft_s_cold`` at 'small' scale,
    because injecting ~0.5 MB/page over PCIe/DRAM is cheaper than
    recomputing ~0.8k tokens of prefill FLOPs (break-even priced in
    SCALING.md "Memory hierarchy arithmetic").  Eviction is forced
    the honest way — a bounded page pool plus distinct-content churn
    traffic — not by poking allocator internals, so the row exercises
    the same spill-on-evict path production would.

    The fleet half is a correctness drill, not a throughput number:
    a two-replica Router with the prefix directory on, one replica
    killed mid-traffic — requests_lost must be 0 and every token
    identical to a ``prefix_directory=False`` oracle fleet (the
    directory may only change WHERE work runs, never what it emits).
    """
    import tempfile

    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.resil import FaultPlan
    from dtdl_tpu.resil.faults import replica_site
    from dtdl_tpu.serve import InferenceEngine, Request, Router, Scheduler

    model = transformer_lm(size, attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    rng = np.random.default_rng(0)
    n_sys = (3 * model.max_seq // 4) // page_size * page_size
    n_sys_pages = n_sys // page_size
    system = rng.integers(0, model.vocab_size, n_sys).tolist()
    suffix = lambda: rng.integers(0, model.vocab_size,
                                  page_size // 2).tolist()
    churn_prompt = lambda: rng.integers(0, model.vocab_size,
                                        n_sys + page_size // 2).tolist()
    # pool = cached system pages + one in-flight churn request, minus a
    # deficit that forces the allocator to evict (and thus spill) —
    # two churn waves push the WHOLE system chain out of HBM
    per_req = n_sys_pages + 2
    engine = InferenceEngine(model, params, n_slots=2,
                             page_size=page_size,
                             n_pages=n_sys_pages + per_req + 2)
    host_budget = 64 << 20

    def ttft(sched, prompt):
        r = Request(prompt, new_tokens)
        sched.run([r])
        assert r.error is None, r.error
        return round(r.t_first - r.t_submit, 6)

    def churn(sched, waves=2):
        for _ in range(waves):
            sched.run([Request(churn_prompt(), new_tokens)])

    def phases(**spill_kw):
        s = Scheduler(engine, harvest_lag=1, **spill_kw)
        cold = ttft(s, system + suffix())
        hbm = ttft(s, system + suffix())
        churn(s)
        hot = ttft(s, system + suffix())
        return cold, hbm, hot, s.metrics.summary()

    # warmup: one full cycle compiles every bucket + the extract/inject
    # variants, so the timed phases below measure work, not compiles
    phases(spill_host_bytes=host_budget)

    cold, hbm, host_hit, m = phases(spill_host_bytes=host_budget)
    with tempfile.TemporaryDirectory() as tmp:
        _, _, disk_hit, md = phases(spill_host_bytes=1, spill_dir=tmp,
                                    spill_disk_bytes=1 << 30)

    row = {
        "model": "kv_hierarchy", "size": size, "page_size": page_size,
        "system_tokens": n_sys, "new_tokens": new_tokens,
        "ttft_s_cold": cold,
        "ttft_s_hbm_hit": hbm,
        "ttft_s_host_hit": host_hit,
        "ttft_s_disk_hit": disk_hit,
        "restore_beats_recompute": host_hit < cold,
        "kv_spill_pages_spilled": m["pages_spilled"],
        "kv_spill_pages_restored": m["pages_restored"],
        "kv_spill_bytes": m["spill_bytes"],
        "kv_spill_restore_s": m["restore_s"],
        "kv_spill_host_hits": m["spill_host_hits"],
        "kv_spill_disk_hits": md["spill_disk_hits"],
    }

    # --- fleet prefix-directory drill (tiny model: correctness only) --
    tiny = transformer_lm("tiny", vocab_size=64, d_model=32, n_layers=2,
                          n_heads=2, d_ff=64, max_seq=48,
                          attn_impl="dense", dtype=jnp.float32)
    tparams = nn.unbox(tiny.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"])
    teng = InferenceEngine(tiny, tparams, n_slots=2, buckets=(8, 16),
                           page_size=8)
    sys9 = list(range(1, 10))
    reqs = lambda: [Request(sys9 + [20 + i, 21 + i], 4)
                    for i in range(6)]
    fkw = dict(sched_kwargs={"harvest_lag": 1}, retry_budget=3,
               probe_interval_s=0.01, watchdog_s=0.15)
    with Router(teng, n_replicas=2, prefix_directory=False,
                **fkw) as off:
        off.run(reqs())
        want = [r.tokens for r in off.run(reqs())]
    plan = FaultPlan().at(replica_site(0, "loop"), 0)
    with Router(teng, n_replicas=2, plan=plan, auto_restart=True,
                **fkw) as router:
        router.run(reqs())                 # replica 0 dies mid-wave
        time.sleep(0.05)
        wave2 = router.run(reqs())
        fs = router.summary()
    row.update({
        "prefix_directory_hits": fs["fleet_directory_hits"],
        "prefix_directory_tokens_saved":
            fs["fleet_directory_tokens_saved"],
        "prefix_directory_invalidations":
            fs["fleet_directory_invalidations"],
        "prefix_directory_requests_lost":
            0 if fs["fleet_accounting_ok"]
            and fs["fleet_requests_failed"] == 0
            and fs["fleet_requests_expired"] == 0
            else fs["fleet_requests_failed"] + fs["fleet_requests_expired"],
        "prefix_directory_token_divergence": sum(
            1 for r, w in zip(wave2, want) if r.tokens != w),
        "prefix_directory_evictions": fs["fleet_evictions"],
    })
    return row


def bench_chunked_prefill(size: str = "small", n_slots: int = 4,
                          chunk_tokens: int = 4,
                          new_tokens: int = 32) -> dict:
    """Chunked-prefill interference row (ISSUE 14 acceptance).

    The workload is the interference shape Sarathi-Serve targets:
    short requests decode steadily while LONG prompts arrive mid-run.
    With whole-prompt prefill, each long admission stalls every
    in-flight decode by a full prefill latency — the decoders' p99
    inter-token gap IS the prefill time.  With ``chunk_tokens`` the
    prompt rides per-step verify chunks sharing the decoders' compiled
    step, so the tail collapses to ~one chunk of extra compute per
    step.  Driven at ``harvest_lag=0`` so each step delivers exactly
    one token per decoding slot and the per-step wall time is the
    honest inter-token latency sample; p50/p99 are over those steps.
    Greedy token identity between the two runs is asserted into the
    row (``token_identical``) — chunking must change WHEN tokens
    appear, never WHICH.  ``decode_steps_delayed_by_prefill`` /
    ``prefill_chunks`` are the mechanism receipts.

    The default ``chunk_tokens=4`` is this COMPUTE-BOUND box's knee
    (measured ~1.7x p99 improvement; 8 gives ~1.25x, 32+ inverts): on
    CPU a chunk step pays the verify window as real compute, so small
    chunks win.  On TPU the verify sweep rides the bandwidth-bound
    parameter read (the spec-decode argument) and the trade curve
    moves toward Sarathi-sized budgets (hundreds of tokens) — the
    SCALING.md round-19 arithmetic.

    The row also carries a ``disagg`` receipt at 'tiny' scale: a
    prefill+decode role fleet (page-granular KV handoff through the
    Router) vs the single mixed scheduler — token-identical, with the
    migration/handoff counters.  One box cannot show the real
    disaggregation win (prefill and decode contend for the same CPU);
    the isolation claim is priced in SCALING.md round 19.
    """
    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.serve import (InferenceEngine, Request, Router,
                                Scheduler)

    model = transformer_lm(size, attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    engine = InferenceEngine(model, params, n_slots=n_slots)
    rng = np.random.default_rng(0)
    long_len = 3 * model.max_seq // 4
    steady_prompts = [rng.integers(0, model.vocab_size, 24).tolist()
                      for _ in range(2)]
    long_prompts = [rng.integers(0, model.vocab_size, long_len).tolist()
                    for _ in range(2)]

    def run(chunk):
        sched = Scheduler(engine, harvest_lag=0, chunk_tokens=chunk)
        steady = [Request(list(p), 3 * new_tokens)
                  for p in steady_prompts]
        for r in steady:
            sched.submit(r)
        gaps = []
        for i in range(6 * new_tokens):
            if i == 4:                 # long prompts land mid-decode
                for p in long_prompts:
                    sched.submit(Request(list(p), 4))
            t0 = time.perf_counter()
            sched.step()
            gaps.append(time.perf_counter() - t0)
            if all(r.done for r in steady):
                break
        sched.shutdown(drain=True)
        arr = np.sort(np.asarray(gaps))
        pick = lambda q: float(arr[int(q * (len(arr) - 1))])  # noqa: E731
        return (pick(0.5), pick(0.99), sched.metrics.summary(),
                [r.tokens for r in steady])

    run(None)                          # warmup: compile both flavors
    run(chunk_tokens)
    p50_w, p99_w, m_w, toks_w = run(None)
    p50_c, p99_c, m_c, toks_c = run(chunk_tokens)

    # disaggregation receipt at 'tiny' scale: identity + handoff books
    tmodel = transformer_lm("tiny", attn_impl="dense",
                            dtype=jnp.float32)
    tparams = nn.unbox(tmodel.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"])
    peng = InferenceEngine(tmodel, tparams, n_slots=2, page_size=16)
    dprompts = [rng.integers(0, tmodel.vocab_size,
                             int(n)).tolist()
                for n in rng.integers(5, 20, 6)]
    refs = [Request(list(p), 8) for p in dprompts]
    Scheduler(peng, harvest_lag=1).run(refs)
    with Router(peng, roles=["prefill", "decode"],
                sched_kwargs={"harvest_lag": 1, "chunk_tokens": 16},
                probe_interval_s=0.01, watchdog_s=1.0) as router:
        reqs = router.run([Request(list(p), 8) for p in dprompts])
        fs = router.summary()
    disagg_identical = all(
        r.error is None and r.tokens == ref.tokens
        for r, ref in zip(reqs, refs))
    handoff_s = sum(rep["kv_handoff_s"] for rep in fs["replicas"])

    return {
        "model": "chunked_prefill", "size": size,
        "chunk_tokens": chunk_tokens,
        "token_identical": toks_w == toks_c,
        "whole": {
            "p50_tok_latency_s": round(p50_w, 6),
            "p99_tok_latency_s": round(p99_w, 6),
            "decode_steps_delayed_by_prefill":
                m_w["decode_steps_delayed_by_prefill"],
        },
        "chunked": {
            "p50_tok_latency_s": round(p50_c, 6),
            "p99_tok_latency_s": round(p99_c, 6),
            "prefill_chunks": m_c["prefill_chunks"],
            "chunk_tokens_total": m_c["chunk_tokens"],
            "decode_steps_delayed_by_prefill":
                m_c["decode_steps_delayed_by_prefill"],
        },
        "p99_improvement_x": round(p99_w / p99_c, 3) if p99_c else None,
        "disagg": {
            "token_identical": disagg_identical,
            "migrations": fs["fleet_migrations"],
            "kv_handoff_pages": fs["fleet_kv_handoff_pages"],
            "kv_handoff_s_mean": round(
                handoff_s / max(1, fs["fleet_migrations"]), 6),
            "accounting_ok": fs["fleet_accounting_ok"],
        },
    }


def bench_multitenant(n_slots: int = 4, new_tokens: int = 32,
                      n_adapters: int = 4, rank: int = 8) -> dict:
    """Multi-tenant serving row (round 22): the cost of tenancy.

    Three questions, each against its own control through the SAME
    scheduler on one LoRA-capable engine (adapter ids / grammar masks
    are data, so every config below reuses ONE compiled program set):

    * **multi-LoRA** — delivered tokens/sec with every request on the
      base model, all on ONE adapter, and round-robined across N
      adapters.  The N-adapter rate over the 1-adapter rate is the
      batching claim: tenancy costs a bank gather, not a batch split
      (a per-tenant engine would divide throughput by N).
    * **grammar** — unconstrained vs JSON-schema-constrained decode.
      The constrained run pays a host-side DFA advance per harvested
      token and a [B, V] mask upload per step, both off the device's
      critical path; the ratio prices them.
    * **streaming** — mean time-to-first-STREAMED-token beside the
      engine TTFT: the stream delivers at the first lag-harvest
      boundary, so the gap is ~harvest_lag steps, not a new sync.
    """
    import os
    import tempfile

    import flax.linen as nn
    from dtdl_tpu.ckpt import save_weights
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.serve import (InferenceEngine, Request, Scheduler,
                                TokenStream, adapter_template, byte_vocab,
                                compile_json_schema)

    model = transformer_lm("tiny", attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="bench_lora_")
    tpl = adapter_template(params, rank=rank)
    paths = []
    for i in range(n_adapters):
        tree = jax.tree_util.tree_map(
            lambda x: np.asarray(rng.normal(0, 0.02, x.shape),
                                 np.float32), tpl)
        p = os.path.join(tmp, f"tenant_{i}")
        save_weights(p, tree)
        paths.append(p)
    engine = InferenceEngine(model, params, n_slots=n_slots,
                             lora_rank=rank,
                             lora_adapters=n_adapters + 1)
    prompts = [rng.integers(0, model.vocab_size, int(n)).tolist()
               for n in rng.integers(8, 16, 2 * n_slots)]
    eos = model.vocab_size - 1
    dfa = compile_json_schema(
        {"type": "object",
         "properties": {"a": {"type": "integer"},
                        "b": {"type": "string"}},
         "required": ["a", "b"]},
        byte_vocab(model.vocab_size), eos_id=eos)

    def run(tenants=(None,), grammar=None, stream=False):
        first_cb = {}

        def mk_stream(i):
            if not stream:
                return None
            return TokenStream(callback=lambda new, i=i: first_cb
                               .setdefault(i, time.perf_counter()))

        reqs = [Request(list(p), new_tokens,
                        adapter=tenants[i % len(tenants)],
                        grammar=grammar,
                        eos_id=(eos if grammar is not None else None),
                        stream=mk_stream(i))
                for i, p in enumerate(prompts)]
        t0 = {r.rid: time.perf_counter() for r in reqs}
        sched = Scheduler(engine, harvest_lag=2)
        sched.run(reqs)
        s = sched.metrics.summary()
        if stream:
            gaps = [first_cb[i] - t0[r.rid]
                    for i, r in enumerate(reqs) if i in first_cb]
            s["ttfst_s_mean"] = round(float(np.mean(gaps)), 6) \
                if gaps else None
        return s

    run()                                       # warmup: compile + bank
    base = run()
    one = run(tenants=(paths[0],))
    many = run(tenants=[None] + paths)
    con = run(grammar=dfa)
    strm = run(stream=True)
    tps = "decode_tokens_per_sec"
    return {
        "model": "multitenant", "n_slots": n_slots,
        "n_adapters": n_adapters, "rank": rank,
        "lora": {
            "base_tokens_per_sec": base[tps],
            "one_adapter_tokens_per_sec": one[tps],
            "n_adapters_tokens_per_sec": many[tps],
            "bank_loads": engine.adapter_bank.n_loads,
            "tokens_by_adapter": many["tokens_by_adapter"],
        },
        "grammar": {
            "free_tokens_per_sec": base[tps],
            "constrained_tokens_per_sec": con[tps],
            "grammar_rejected_tokens": con["grammar_rejected_tokens"],
            "dfa_states": dfa.n_states,
            "dfa_bytes": dfa.nbytes(),
        },
        "stream": {
            "ttft_s_mean": strm["ttft_s_mean"],
            "ttfst_s_mean": strm["ttfst_s_mean"],
            "stream_deliveries": strm["stream_deliveries"],
        },
        "compiled_decode_programs": engine.compile_stats()["decode"],
    }


def bench_quant(model, params, n_slots: int = 4, page_size: int = 32,
                new_tokens: int = 48) -> list:
    """Quantized-serving sweep: f32 / w8 / w8+kv8 / w8f+kvf8 ×
    dense/paged (ISSUE 7 acceptance; fp8 rows kernel round 2).

    Eight engines over the same tiny model and traffic, scheduler-driven
    like the spec/paged rows (warmup run compiles, second run is timed).
    Decode is HBM-bandwidth-bound, so on TPU tokens/sec tracks the
    ``bytes_per_token`` receipt each row carries from
    ``compile_stats()['quant']`` — ``(param_bytes + kv_arena_bytes) /
    n_slots``, the roofline numerator.  On this CPU box the timing is
    honest but NOT the roofline: XLA:CPU pays the int8→f32 convert as
    real compute instead of hiding it under an HBM read, so the w8 rows
    can be slower than f32 here while the byte receipts — the thing
    that transfers to TPU — shrink ~4x (f32 weights) and >2x (KV arena;
    SCALING.md "Quantized serving arithmetic").  The paged rows all get
    the SAME ``kv_pool_bytes`` budget (the f32 dense-equivalent pool),
    so the int8 row's ``n_pages`` IS the capacity-multiplier receipt:
    slots-per-HBM-byte, measured in pages, at fixed bytes.  The fp8
    rows (``quantize_weights='w8f'`` / ``kv_dtype='fp8'``) keep the
    one-byte payloads and shrink the *sidecars* — bf16 scales vs int8's
    f32 — so the DENSE fp8 row's bytes_per_token must land strictly
    below the dense w8kv8 row, and the PAGED fp8 row (whose arena
    always fills the fixed budget) must hold strictly more pages than
    the int8 one (the kernel-round-2 acceptance receipts).
    """
    from dtdl_tpu.serve import InferenceEngine, Request, Scheduler

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size, int(n)).tolist()
               for n in rng.integers(8, 16, n_slots)]
    new_tokens = min(new_tokens, model.max_seq - 16)
    # one fixed HBM budget for every paged row: what the f32 pool needs
    # at dense-equivalent capacity
    probe = InferenceEngine(model, params, n_slots=n_slots,
                            page_size=page_size)
    pool_budget = probe.page_bytes * probe.n_pages
    out = []
    for arena in ("dense", "paged"):
        for label, w8, kv in (("f32", False, None),
                              ("w8", True, None),
                              ("w8kv8", True, "int8"),
                              ("w8fkvf8", "w8f", "fp8")):
            kw = (dict(page_size=page_size, kv_pool_bytes=pool_budget)
                  if arena == "paged" else {})
            engine = InferenceEngine(model, params, n_slots=n_slots,
                                     quantize_weights=w8, kv_dtype=kv,
                                     **kw)

            def run():
                reqs = [Request(p, new_tokens) for p in prompts]
                sched = Scheduler(engine, harvest_lag=1)
                sched.run(reqs)
                return sched.metrics.summary()

            run()                  # warmup: compile prefill + decode
            s = run()              # timed
            q = engine.compile_stats()["quant"]
            out.append({
                "arena": arena, "weights": label,
                "kv_dtype": q["kv_dtype"] or "f32",
                "decode_tokens_per_sec": s["decode_tokens_per_sec"],
                "ttft_s_mean": s["ttft_s_mean"],
                "param_bytes": q["param_bytes"],
                "kv_arena_bytes": q["kv_arena_bytes"],
                "bytes_per_token": q["decode_hbm_bytes_per_token"],
                "n_pages": engine.n_pages,
            })
    return out


def bench_paged_kernel(page_size: int = 8, n_ptab: int = 8, batch: int = 4,
                       heads: int = 4, head_dim: int = 64,
                       widths=(1, 5), iters: int = 3) -> dict:
    """Isolated paged-attend microbench: dense vs gather-paged vs the
    Pallas paged kernel, at decode (S=1) and verify (S=k+1) widths
    (kernel round 2 acceptance).

    Three jitted attends over the SAME pooled arena geometry
    ``[n_pages, H, page, D]`` and per-slot page tables, quant off and
    int8 (fused scales):

    * **dense** — attend over a contiguously materialized
      [B, H, S_ctx, D] K/V (the no-paging floor: same FLOPs, no
      indirection).
    * **gather** — ``jnp.take`` the slot's whole page-table worth of
      pages out of the pool, then attend (what the engine's gather path
      does per step: the pool crosses HBM into a scratch copy and again
      into the attend).
    * **kernel** — ``dtdl_tpu.ops.paged_attention``: the grid walks the
      page table *inside* the kernel, DMA-ing only live pages pool→VMEM
      once, scales folded into tile loads.

    The TPU claim is the **bytes column**, not this box's ms: per step
    the gather path moves ``2·B·n_ptab·page·H·D`` payload bytes twice
    (pool→scratch, scratch→compute) while the kernel moves
    ``2·B·ceil((pos+1)/page)·page·H·D`` once — ``bytes_x`` is that
    ratio at the benchmarked occupancy, >1 whenever slots are not at
    max context (and ≥2 even there).  Honesty: on CPU the kernel runs
    under the Pallas interpreter (``interpret: true``), so its ms here
    is interpreter overhead, not a TPU prediction — the v5e re-sweep is
    the verification (LM_ROOFLINE.md §9).
    """
    from dtdl_tpu.ops.paged_attention import paged_attention

    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)
    n_pages = batch * n_ptab + 1
    s_ctx = n_ptab * page_size
    d = head_dim
    pk, pv = (jnp.asarray(rng.normal(size=(n_pages, heads, page_size, d)),
                          jnp.float32) for _ in range(2))
    table = jnp.asarray(
        1 + np.arange(batch * n_ptab).reshape(batch, n_ptab), jnp.int32)
    # mid-range occupancy: slots at ~3/4 context (the shape serving
    # actually runs at — full-context slots are the retirement edge)
    base_pos = 3 * s_ctx // 4 - 1
    active = jnp.ones((batch,), jnp.int32)
    scale = 1.0 / math.sqrt(d)

    def timed(fn, *args):
        fn_j = jax.jit(fn)
        jax.block_until_ready(fn_j(*args))        # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn_j(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    def gather_attend(q, pos):
        k = jnp.take(pk, table, axis=0)           # [B, n_ptab, H, page, D]
        v = jnp.take(pv, table, axis=0)
        k = k.transpose(0, 2, 1, 3, 4).reshape(batch, heads, s_ctx, d)
        v = v.transpose(0, 2, 1, 3, 4).reshape(batch, heads, s_ctx, d)
        return _masked_attend(q, k, v, pos)

    def _masked_attend(q, k, v, pos):
        s_new = q.shape[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
        cols = jnp.arange(s_ctx)[None, None, None, :]
        qpos = (pos[:, None, None, None]
                + jnp.arange(s_new)[None, None, :, None])
        s = jnp.where(cols <= qpos, s * scale, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)

    k_dense = jnp.take(pk, table, axis=0).transpose(0, 2, 1, 3, 4) \
        .reshape(batch, heads, s_ctx, d)
    v_dense = jnp.take(pv, table, axis=0).transpose(0, 2, 1, 3, 4) \
        .reshape(batch, heads, s_ctx, d)

    it = 4                                        # f32 payload bytes
    rows = []
    for s_new in widths:
        pos = jnp.full((batch,), base_pos - (s_new - 1), jnp.int32)
        q = jnp.asarray(rng.normal(size=(batch, heads, s_new, d)),
                        jnp.float32)
        dense_s = timed(lambda q, pos: _masked_attend(q, k_dense, v_dense,
                                                      pos), q, pos)
        gather_s = timed(gather_attend, q, pos)
        kernel_s = timed(
            lambda q, pos: paged_attention(q, pk, pv, table, pos, active,
                                           scale=scale), q, pos)
        live_pages = int(np.ceil((base_pos + 1) / page_size))
        gather_bytes = 2 * 2 * batch * n_ptab * page_size * heads * d * it
        kernel_bytes = 2 * batch * live_pages * page_size * heads * d * it
        rows.append({
            "s_new": s_new, "phase": "decode" if s_new == 1 else "verify",
            "dense_ms": round(dense_s * 1e3, 3),
            "gather_ms": round(gather_s * 1e3, 3),
            "kernel_ms": round(kernel_s * 1e3, 3),
            "gather_hbm_bytes": gather_bytes,
            "kernel_hbm_bytes": kernel_bytes,
            "bytes_x": round(gather_bytes / kernel_bytes, 3),
        })
    return {"model": "paged_kernel", "interpret": interpret,
            "page_size": page_size, "n_ptab": n_ptab, "batch": batch,
            "heads": heads, "head_dim": head_dim, "iters": iters,
            "occupancy": round((base_pos + 1) / s_ctx, 3), "rows": rows}


def bench_fleet(n_requests: int = 24, new_tokens: int = 24) -> dict:
    """Fleet row (ISSUE 9): Router throughput at 1 vs 2 replicas, plus
    a kill-one-replica failover drill.

    Throughput: the same synthetic traffic driven through the Router's
    least-loaded dispatch over thread-hosted replicas SHARING one
    engine (XLA executions release the GIL, so two replicas can overlap
    device work; at tiny scale host dispatch dominates, so treat the
    ratio as a lower bound — on real HBM-bound decode each replica is
    its own device and the scaling is near-linear by construction).

    Failover: a loop-site fault kills replica 0's worker mid-traffic.
    Receipts: ``time_to_evict_s`` (worker death → the EVICTED health
    transition, i.e. detection latency through the watchdog/probe
    path), ``requests_retried``, and ``requests_lost`` — which must be
    ZERO: every accepted request reaches a terminal state, retried ones
    token-identical by greedy determinism (the fleet invariant,
    tests/test_fleet.py)."""
    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.resil import FaultPlan
    from dtdl_tpu.resil.faults import replica_site
    from dtdl_tpu.serve import InferenceEngine, Request, Router, Scheduler

    model = transformer_lm("tiny", attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    engine = InferenceEngine(model, params, n_slots=4, buckets=(64,))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size,
                            int(rng.integers(8, 64))).tolist()
               for _ in range(n_requests)]

    def traffic():
        return [Request(list(p), new_tokens) for p in prompts]

    # warm the compiled programs outside every timed region
    Scheduler(engine, harvest_lag=4).run(
        [Request(list(prompts[0]), 4)])

    row = {"model": "fleet", "n_requests": n_requests,
           "new_tokens": new_tokens, "replicas": []}
    for n_rep in (1, 2):
        with Router(engine, n_replicas=n_rep,
                    sched_kwargs={"harvest_lag": 4}) as router:
            t0 = time.perf_counter()
            router.run(traffic(), timeout_s=600)
            wall = time.perf_counter() - t0
            s = router.summary()
        row["replicas"].append({
            "n_replicas": n_rep,
            "wall_s": round(wall, 4),
            "decode_tokens_per_sec": round(
                s["fleet_decode_tokens"] / wall, 1) if wall else 0.0,
            "ttft_s_p50": s.get("fleet_ttft_s_p50", 0.0),
            "ttft_s_p99": s.get("fleet_ttft_s_p99", 0.0),
        })

    # the failover drill: kill replica 0's worker on its 4th iteration
    plan = FaultPlan().at(replica_site(0, "loop"), 3)
    with Router(engine, n_replicas=2, plan=plan, retry_budget=4,
                watchdog_s=0.2, probe_interval_s=0.02,
                sched_kwargs={"harvest_lag": 4}) as router:
        router.run(traffic(), timeout_s=600)
        s = router.summary()
        evict = router.evict_log[0] if router.evict_log else {}
    lost = (s["fleet_requests_submitted"]
            - (s["fleet_requests_finished"] + s["fleet_requests_rejected"]
               + s["fleet_requests_expired"] + s["fleet_requests_failed"]
               + s["fleet_requests_aborted"]))
    row["failover"] = {
        "time_to_evict_s": evict.get("detect_latency_s"),
        "requests_retried": s["fleet_retries"],
        "requests_failed": s["fleet_requests_failed"],
        "requests_lost": lost,
        "evictions": s["fleet_evictions"],
        "restarts": s["fleet_restarts"],
    }
    return row


def bench_store_rpc(n_ops: int = 300) -> dict:
    """Store RPC microbench (ISSUE 13): per-verb latency of the
    control-plane store, local (``HostKVStore`` — a lock and a dict)
    vs TCP (``TCPStoreClient`` against a localhost
    ``TCPStoreServer`` — framing + a socket round trip).  The gap IS
    the price of a real multi-process control plane, and the number
    SCALING.md's heartbeat-period arithmetic divides by: a verb's p99
    must sit far under ``heartbeat_s`` or the liveness layer's beat
    thread falls behind its own lease."""
    from dtdl_tpu.obs.hist import LogHistogram
    from dtdl_tpu.parallel.kvstore import HostKVStore
    from dtdl_tpu.parallel.tcpstore import TCPStoreClient, TCPStoreServer

    def drive(store):
        hists = {v: LogHistogram() for v in ("set", "get", "add")}
        ops = {"set": lambda i: store.set(f"k{i % 32}", i),
               "get": lambda i: store.get(f"k{i % 32}", None),
               "add": lambda i: store.add("ctr")}
        for verb, h in hists.items():
            for i in range(n_ops):
                t0 = time.perf_counter()
                ops[verb](i)
                h.add(time.perf_counter() - t0)
        return {verb: h.summary(unit=1e6, digits=2)   # microseconds
                for verb, h in hists.items()}

    row = {"model": "store_rpc", "n_ops": n_ops}
    row["local"] = drive(HostKVStore())
    server = TCPStoreServer().start()
    try:
        row["tcp"] = drive(TCPStoreClient(server.addr))
    finally:
        server.stop()
    return row


def bench_elastic(n_workers: int = 4, steps: int = 12,
                  overhead_steps: int = 24, reps: int = 3,
                  backend: str = "host") -> dict:
    """Elastic-training row (ISSUE 12): the kill-one-of-N drill's MTTR
    decomposition plus the liveness-layer overhead receipt.

    ``backend`` selects the control-plane store (ISSUE 13): ``host``
    is the PR 12 in-process ``HostKVStore``; ``tcp`` runs the SAME
    drill through a localhost ``TCPStoreServer`` + per-world
    ``TCPStoreClient`` — the elastic_tcp row's MTTR sits beside the
    in-process one, so the cost of real sockets on the recovery path
    is a printed number, not a guess.

    Drill: ``n_workers`` thread-hosted ElasticWorkers train a tiny MLP
    through the host control-plane store; ``peer_site`` kills one
    mid-run.  Receipts decompose MTTR exactly as SCALING.md's failure
    model does: ``detect_s`` (victim death → first survivor's named
    PeerLostError; bounded by watchdog_s + a poll slice), ``reform_s``
    (abort → new-generation world formed), ``restore_s`` (world →
    committed snapshot restored), ``first_step_s`` (restore → first
    applied step of the shrunken world), and ``mttr_s`` = death → first
    new step.  ``samples_lost``/``samples_double_counted`` audit the
    effective timeline against the world-size-agnostic sampler and must
    both be ZERO.

    Overhead: the same 2-worker world with the heartbeat lease layer on
    vs off (interleaved best-of-``reps``); the liveness layer is
    host-threads-only — zero device syncs by construction — so
    ``liveness_overhead_frac`` must sit inside the obs <2% contract.
    """
    from dtdl_tpu.data.sharding import GlobalBatchSampler
    from dtdl_tpu.models import MLP
    from dtdl_tpu.parallel.kvstore import HostKVStore, RetryingStore
    from dtdl_tpu.parallel.tcpstore import TCPStoreClient, TCPStoreServer
    from dtdl_tpu.resil import (ElasticConfig, ElasticWorker, FaultPlan,
                                effective_sample_log, peer_site,
                                run_workers)
    from dtdl_tpu.train import init_state

    if backend not in ("host", "tcp"):
        raise ValueError(f"unknown store backend {backend!r}")
    servers = []

    def mk_store():
        if backend == "host":
            return HostKVStore()
        srv = TCPStoreServer().start()
        servers.append(srv)
        return TCPStoreClient(srv.addr)

    n_ex, dim, gbatch = 96, 16, 12
    rng = np.random.default_rng(0)
    x_all = rng.normal(size=(n_ex, dim)).astype(np.float32)
    y_all = rng.integers(0, 10, n_ex)
    model = MLP(n_units=8)
    state0 = init_state(model, jax.random.PRNGKey(0),
                        jnp.zeros((1, dim)), optax.sgd(0.1))

    def loss(p, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply({"params": p}, b["x"]), b["y"]).mean()

    grad_jit = jax.jit(lambda p, b: jax.grad(loss)(p, b))
    apply_jit = jax.jit(lambda s, g, n: s.apply_gradients(
        grads=jax.tree.map(lambda v: v / n, g)))
    grad_fn = lambda s, b: grad_jit(s.params, b)          # noqa: E731
    apply_fn = lambda s, g, n: apply_jit(s, g, float(n))  # noqa: E731
    batch_fn = lambda i: {"x": jnp.asarray(x_all[i]),     # noqa: E731
                          "y": jnp.asarray(y_all[i])}
    # warm the compiled step outside every timed region (a first-call
    # compile inside a worker reads as a wedge to the step deadline)
    apply_fn(state0, jax.device_get(grad_fn(state0,
                                            batch_fn(np.arange(4)))), 2)

    def mk_world(store, ranks, n_steps, cfg, ckpt_dir=None):
        sampler = GlobalBatchSampler(n_ex, gbatch, seed=3)
        return [ElasticWorker(
            RetryingStore(store), r, init_fn=lambda: state0,
            grad_fn=grad_fn, apply_fn=apply_fn, batch_fn=batch_fn,
            sampler=sampler, total_steps=n_steps, cfg=cfg,
            ckpt_dir=ckpt_dir, audit_samples=True) for r in ranks]

    row = {"model": "elastic" if backend == "host" else "elastic_tcp",
           "n_workers": n_workers, "steps": steps, "backend": backend}

    # ---- liveness-layer overhead: heartbeats on vs off ----------------
    def world_wall(heartbeat_s):
        cfg = ElasticConfig(heartbeat_s=heartbeat_s, watchdog_s=0.5,
                            step_timeout_s=30.0, join_grace_s=0.1,
                            snapshot_every=10 ** 9)
        ws = mk_world(mk_store(), list(range(2)), overhead_steps, cfg)
        t0 = time.perf_counter()
        run_workers(ws, timeout_s=300)
        assert all(w.done for w in ws)
        return time.perf_counter() - t0

    on = min(world_wall(0.02) for _ in range(reps))
    off = min(world_wall(0.0) for _ in range(reps))
    row["liveness"] = {
        "steps": overhead_steps,
        "wall_on_s": round(on, 4), "wall_off_s": round(off, 4),
        "steps_per_sec": round(overhead_steps / on, 1),
        "overhead_frac": round(max(0.0, 1.0 - off / on), 4),
    }

    # ---- the kill-one-of-N drill --------------------------------------
    cfg = ElasticConfig(heartbeat_s=0.02, watchdog_s=0.2,
                        step_timeout_s=5.0, join_grace_s=0.1,
                        snapshot_every=2)
    victim_rank, kill_at = n_workers - 2, steps // 2
    plan = FaultPlan().at(peer_site(victim_rank, "step"), kill_at,
                          "crash")
    store = mk_store()
    ckpt_dir = tempfile.mkdtemp(prefix="bench_elastic_")
    with plan:
        ws = mk_world(store, list(range(n_workers)), steps, cfg,
                      ckpt_dir=ckpt_dir)
        run_workers(ws, timeout_s=300)
    victim = ws[victim_rank]
    survivors = [w for w in ws if w.rank != victim_rank]
    assert all(w.done for w in survivors), "survivors must finish"

    def first(w, name, **match):
        for n, t, info in w.events:
            if n == name and all(info.get(k) == v
                                 for k, v in match.items()):
                return t
        return None

    detects = [first(w, "peer_lost") for w in survivors]
    worlds1 = [first(w, "world", generation=1) for w in survivors]
    restores = [first(w, "restore") for w in survivors]
    applied1 = [first(w, "applied", generation=1) for w in survivors]
    t_dead = victim.stopped_t
    detect = min(detects) - t_dead
    reform = max(worlds1) - min(detects)
    restore = max(restores) - max(worlds1)
    first_step = max(applied1) - max(restores)
    # sample-level accounting over what the workers ACTUALLY consumed
    # (audit_samples logs the fed shard indices): compare the effective
    # timeline's multiset against the sampler's pure stream per step
    eff = effective_sample_log(ws)
    sampler = GlobalBatchSampler(n_ex, gbatch, seed=3)
    lost = dups = 0
    for s in range(steps):
        want = Counter(sampler.batch_indices(s).tolist())
        got = Counter(eff[s].tolist()) if s in eff else Counter()
        lost += sum((want - got).values())
        dups += sum((got - want).values())
    row["drill"] = {
        "victim": victim_rank, "kill_at_step": kill_at,
        "world_after": len(survivors),
        "detect_s": round(detect, 4),
        "reform_s": round(reform, 4),
        "restore_s": round(restore, 4),
        "first_step_s": round(first_step, 4),
        "mttr_s": round(max(applied1) - t_dead, 4),
        "watchdog_s": cfg.watchdog_s,
        "samples_lost": lost,
        "samples_double_counted": dups,
    }
    for srv in servers:
        srv.stop()
    return row


def bench_obs_pipeline(n_requests: int = 24, new_tokens: int = 24,
                       reps: int = 4) -> dict:
    """Fleet-era observability receipt (ISSUE 11): the SAME serve
    traffic with the full pipeline off vs ON — request-correlated
    tracing (per-request events + flow markers), the continuous
    metrics exporter sampling window deltas at harvest/drain
    boundaries, and the SLO evaluator judging every sampled point.

    The contract is the PR-3 bar: ``overhead_frac`` (1 - on/off decode
    tokens/sec) stays under 2% with ZERO added per-token syncs — the
    pipeline touches host counters at request-lifecycle and boundary
    granularity only, never per token (structurally pinned by
    tests/test_obs_export.py re-running the compile-receipt suite with
    the pipeline on).  Driven through the single-threaded Scheduler so
    the measurement is the hot decode path, not thread-scheduling noise
    (the Router layer adds host work per REQUEST, measured separately
    in the fleet row); interleaved best-of-``reps`` against this box's
    ambient drift, like the robustness row."""
    import flax.linen as nn
    from dtdl_tpu.models import transformer_lm
    from dtdl_tpu.obs import (MetricsExporter, Observer, SLO,
                              SLOEvaluator)
    from dtdl_tpu.serve import InferenceEngine, Request, Scheduler

    model = transformer_lm("tiny", attn_impl="dense", dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])
    engine = InferenceEngine(model, params, n_slots=4, buckets=(64,))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size,
                            int(rng.integers(8, 64))).tolist()
               for _ in range(n_requests)]
    # warm the compiled programs outside every timed region
    Scheduler(engine, harvest_lag=4).run([Request(list(prompts[0]), 4)])

    def run_off():
        sched = Scheduler(engine, harvest_lag=4)
        t0 = time.perf_counter()
        sched.run([Request(list(p), new_tokens) for p in prompts])
        dt = time.perf_counter() - t0
        return sched.metrics.summary()["decode_tokens"] / dt, None

    def run_on():
        obs = Observer(trace=True, sentinel="warn")
        exporter = MetricsExporter(interval_s=0.05)
        exporter.attach_slo(SLOEvaluator([
            SLO("ttft_p99", metric="ttft_s_p99", op="<=", target=60.0),
            SLO("availability", good="requests_finished",
                bad=("requests_failed", "requests_expired"),
                target=0.999),
        ], observer=obs))
        sched = Scheduler(engine, harvest_lag=4, observer=obs,
                          exporter=exporter)
        t0 = time.perf_counter()
        sched.run([Request(list(p), new_tokens) for p in prompts])
        dt = time.perf_counter() - t0
        receipts = {
            "trace_events": len(obs.tracer),
            "export_snapshots": exporter.n_snapshots,
            **exporter.slo.summary(),
        }
        return sched.metrics.summary()["decode_tokens"] / dt, receipts

    best = {"off": 0.0, "on": 0.0}
    receipts = None
    run_off(), run_on()           # one warm lap each (allocator, trace)
    for _ in range(reps):
        tps, _ = run_off()
        best["off"] = max(best["off"], tps)
        tps, rec = run_on()
        if tps > best["on"]:
            best["on"], receipts = tps, rec
    return {"model": "obs_pipeline", "n_requests": n_requests,
            "new_tokens": new_tokens,
            "off_tokens_per_sec": round(best["off"], 1),
            "on_tokens_per_sec": round(best["on"], 1),
            "overhead_frac": round(1.0 - best["on"] / best["off"], 4),
            **(receipts or {})}


# ---------------------------------------------------------------------------
# modeled multi-chip scaling (SCALING.md)
#
# This box has ONE tunneled chip; measured multi-chip throughput is not
# possible.  What IS measurable: the single-chip step time and the exact
# gradient byte volume every data-parallel replica must allreduce.  The
# model below turns those into 1->32-chip efficiency curves, with the
# interconnect constants documented as public-spec estimates.
# ---------------------------------------------------------------------------

# Effective allreduce bandwidth per chip over ICI (bytes/s).  v5e has a 2D
# torus with 4 ICI links/chip at ~45 GB/s each per direction; a
# bandwidth-optimal ring allreduce drives 2 links concurrently -> ~90 GB/s
# effective.  DCN: ~200 Gbps (25 GB/s) per host NIC, shared by the host's
# 8 chips; the hierarchical allreduce below accounts for the sharing.
ICI_ALLREDUCE_BW = 90e9
DCN_HOST_BW = 25e9
CHIPS_PER_HOST = 8
# fraction of the backward pass the grad allreduce can hide under (XLA
# overlaps collective-start with remaining backward compute, like DDP's
# bucketed hooks), and backward's share of step time (~2 of 3 passes)
OVERLAP_FRAC = 0.9
BWD_FRAC = 2 / 3


def _allreduce_time(nbytes: float, n: int, bw: float) -> float:
    """Ring/bidirectional-exchange allreduce: 2 * B * (N-1)/N / bw."""
    if n <= 1:
        return 0.0
    return 2.0 * nbytes * (n - 1) / n / bw


def modeled_scaling(step_time_s: float, grad_bytes: float,
                    chips=(1, 2, 4, 8, 16, 32)) -> dict:
    """DDP weak-scaling efficiency: fixed per-chip batch, grads allreduced.

    ``ici``: all chips in one ICI domain (a v5e pod slice).  ``hybrid``:
    8-chip ICI hosts joined over DCN — intra-host reduce-scatter/allgather
    leaves each chip 1/8 of the grads, the DCN stage moves that share
    through 1/8 of the host NIC, then the ICI stage finishes.  Exposed
    time is whatever the overlap window (OVERLAP_FRAC of the backward)
    cannot hide.  Efficiency = t_step / (t_step + exposed).
    """
    def eff(t_comm, overlap):
        window = OVERLAP_FRAC * BWD_FRAC * step_time_s if overlap else 0.0
        exposed = max(0.0, t_comm - window)
        return round(step_time_s / (step_time_s + exposed), 4)

    out = {"ici": {}, "hybrid": {}, "ici_no_overlap": {},
           "hybrid_no_overlap": {}, "comm_ms": {}}
    for n in chips:
        if n > CHIPS_PER_HOST and n % CHIPS_PER_HOST:
            raise ValueError(
                f"chips={n}: counts > {CHIPS_PER_HOST} must be whole hosts "
                f"(multiples of {CHIPS_PER_HOST}) — a partial host would be "
                f"silently dropped from the hybrid model")
        t_ici = _allreduce_time(grad_bytes, n, ICI_ALLREDUCE_BW)
        hosts = max(1, n // CHIPS_PER_HOST)
        t_hyb = _allreduce_time(grad_bytes, min(n, CHIPS_PER_HOST),
                                ICI_ALLREDUCE_BW)
        if hosts > 1:
            # per chip: grad_bytes/8 over its 1/8 share of the host NIC
            t_hyb += _allreduce_time(grad_bytes / CHIPS_PER_HOST, hosts,
                                     DCN_HOST_BW / CHIPS_PER_HOST)
        out["ici"][n] = eff(t_ici, overlap=True)
        out["hybrid"][n] = eff(t_hyb, overlap=True)
        # worst case: nothing hides (the reference's gloo-era regime)
        out["ici_no_overlap"][n] = eff(t_ici, overlap=False)
        out["hybrid_no_overlap"][n] = eff(t_hyb, overlap=False)
        out["comm_ms"][n] = {"ici": round(t_ici * 1e3, 3),
                             "hybrid": round(t_hyb * 1e3, 3)}
    return out


# point-to-point ICI bandwidth (one neighbor link, one direction) — the
# pp ppermute hops and the sp ring ride single links, unlike the
# 2-link ring allreduce above
ICI_P2P_BW = 45e9
# fraction of the sequence-parallel ring traffic NOT hidden under the
# per-chunk attention compute (the zigzag ring overlaps send/recv with
# block attention by construction; 0.5 = half the hops exposed is the
# conservative end measured for flash-block sizes on v5e-class chips)
RING_EXPOSED = 0.5


def modeled_scaling_4d(step_time_s: float, grad_bytes: float, *,
                       d_model: int, n_layers: int, batch: int, seq: int,
                       n_microbatches: int = 8, n_experts: int = 0,
                       capacity_factor: float = 1.25,
                       moe_every: int = 2,
                       meshes=((1, 1, 1, 1), (1, 1, 1, 2), (1, 1, 1, 4),
                               (1, 1, 1, 8), (1, 1, 2, 1), (1, 1, 4, 1),
                               (1, 2, 2, 2), (2, 2, 2, 2),
                               (1, 2, 2, 8))) -> dict:
    """Strong-scaling model for the 4D megatron path (SCALING.md).

    The DDP model above weak-scales a fixed per-chip batch; the 4D
    engine's purpose is the opposite — split ONE model/batch over a
    ('data','seq','pipe','model') mesh.  Per mesh (dp, sp, pp, tp):

    * compute: ``t_step / n`` (the measured single-chip step divided
      over all four axes), inflated by the segmented-1F1B bubble
      ``(pp-1) / (M + pp - 1)`` (the Megatron 1F1B bound at v=1 —
      megatron.bubble_fraction);
    * tp: 4 activation allreduces per owned layer (2 fwd + 2 bwd,
      Megatron column->row pairs) of the local [B/dp · S/sp, D] bf16
      activations over the tp group (ring-allreduce cost);
    * sp: the zigzag ring forwards each chip's K+V shard (sp-1) hops per
      owned layer, ~3x for the backward's re-ring + dKV ring, over
      single ICI links; ``RING_EXPOSED`` of it is not hidden under
      block-attention compute;
    * pp: each chip ppermutes every microbatch's boundary activations
      once forward and once backward (single-link p2p);
    * ep: routed MoE all-to-alls ``cf``-capacity token buffers to the
      expert shards over 'model' — 2 (dispatch+combine) x 2 (fwd+bwd),
      (tp-1)/tp of the tokens leave the chip — on every
      ``moe_every``-th layer;
    * dp: the grad allreduce of this chip's parameter shard
      (``grad_bytes / (pp·tp)`` f32), overlap-windowed like the DDP
      model.

    Efficiency = ideal linear time / modeled time; (1,1,1,1) is exactly
    the measured step (sanity anchor).  Constants: ICI_ALLREDUCE_BW,
    ICI_P2P_BW, RING_EXPOSED, OVERLAP_FRAC/BWD_FRAC above.
    """
    out = {}
    for dp, sp, pp, tp in meshes:
        n = dp * sp * pp * tp
        M = n_microbatches
        act_bytes = batch * seq * d_model * 2 / (dp * sp)   # bf16, local
        layers_owned = n_layers / pp

        bubble = (pp - 1) / (M + pp - 1) if pp > 1 else 0.0
        t_compute = step_time_s / n
        t_pipe = t_compute / (1.0 - bubble)

        t_tp = layers_owned * 4 * _allreduce_time(
            act_bytes, tp, ICI_ALLREDUCE_BW)
        # each of the (sp-1) ring rounds sends this chip's FULL K+V shard
        # (2 * act_bytes — act_bytes is already the per-chip slice, so no
        # (n-1)/n allreduce discount applies to p2p hops)
        t_sp = (RING_EXPOSED * layers_owned * 3 * 2 * act_bytes
                * (sp - 1) / ICI_P2P_BW) if sp > 1 else 0.0
        t_pp = (2 * act_bytes / ICI_P2P_BW) if pp > 1 else 0.0
        t_moe = 0.0
        if n_experts and tp > 1:
            moe_layers = layers_owned / moe_every
            t_moe = (moe_layers * 4 * capacity_factor * act_bytes
                     * (tp - 1) / tp / ICI_P2P_BW)
        dp_grad = _allreduce_time(grad_bytes / (pp * tp), dp,
                                  ICI_ALLREDUCE_BW)
        window = OVERLAP_FRAC * BWD_FRAC * t_pipe
        t_dp = max(0.0, dp_grad - window)

        t_total = t_pipe + t_tp + t_sp + t_pp + t_moe + t_dp
        out[f"{dp},{sp},{pp},{tp}"] = {
            "chips": n,
            "efficiency": round(t_compute / t_total, 4),
            "speedup": round(step_time_s / t_total, 2),
            "step_ms": round(t_total * 1e3, 3),
            "comm_ms": {"tp": round(t_tp * 1e3, 3),
                        "sp": round(t_sp * 1e3, 3),
                        "pp": round(t_pp * 1e3, 3),
                        "moe": round(t_moe * 1e3, 3),
                        "dp_exposed": round(t_dp * 1e3, 3)},
            "bubble": round(bubble, 4),
        }
    return out


def _grad_bytes(model, example) -> float:
    """f32 gradient bytes of one replica (flax keeps params f32 under
    bf16 compute; DDP allreduces full-precision grads).  Only the
    'params' collection counts: BatchNorm running stats are psum-averaged
    separately, not part of the gradient payload."""
    shapes = jax.eval_shape(
        lambda k: model.init(k, example), jax.random.PRNGKey(0))
    return float(sum(np.prod(l.shape) * 4
                     for l in jax.tree.leaves(shapes["params"])
                     if hasattr(l, "shape")))


def scaling_section(records) -> dict:
    """Modeled scaling curves for the headline rows of this bench run,
    plus the reference-sanity point (see SCALING.md)."""
    from dtdl_tpu.models import pyramidnet, resnet50, transformer_lm

    out = {}
    for r in records:
        if "step_time_ms" not in r:
            continue
        key = None
        if r["model"] == "pyramidnet" and r["batch_size"] == 256:
            key, model, ex = ("pyramidnet_bs256", pyramidnet(),
                              jnp.zeros((1, 32, 32, 3)))
        elif r["model"] == "resnet50" and r["batch_size"] == 256:
            key, model, ex = ("resnet50_bs256", resnet50(),
                              jnp.zeros((1, 224, 224, 3)))
        elif r["model"] == "lm" and r.get("size") in ("base", "large"):
            key, model, ex = (f"lm_{r['size']}_seq{r['seq']}",
                              transformer_lm(r["size"], max_seq=r["seq"]),
                              jnp.zeros((1, r["seq"]), jnp.int32))
        if key:
            gb = _grad_bytes(model, ex)
            out[key] = {"grad_mbytes": round(gb / 1e6, 1),
                        **modeled_scaling(r["step_time_ms"] / 1e3, gb)}
            if key.startswith("lm_"):
                # the 4D engine's strong-scaling model, anchored on the
                # same measured step (SCALING.md "The 4D model"); 'large'
                # shows the shape effect — bigger d_model amortizes the
                # tp activation psums over 4x the MXU work
                out[f"megatron_4d_{key[3:]}"] = modeled_scaling_4d(
                    r["step_time_ms"] / 1e3, gb,
                    d_model=model.d_model, n_layers=model.n_layers,
                    batch=r["batch_size"], seq=r["seq"])
    if out:
        # sanity anchor: solving the (no-overlap) model for the
        # reference's published 4-GPU point — PyramidNet, 0.255 s/step,
        # 75% efficiency (reference pytorch/README.md:122-125) — implies
        # an effective allreduce bandwidth of ~1.7 GB/s, plausible for
        # its unoverlapped gloo/PCIe-era allreduce; see SCALING.md
        if "pyramidnet_bs256" in out:   # same grads; skip the re-trace
            gb_ref = out["pyramidnet_bs256"]["grad_mbytes"] * 1e6
        else:
            gb_ref = _grad_bytes(pyramidnet(), jnp.zeros((1, 32, 32, 3)))
        t_ref, eff_ref = 0.255, 0.75
        exposed = t_ref / eff_ref - t_ref
        out["reference_4gpu_sanity"] = {
            "measured_eff": eff_ref,
            "implied_allreduce_gbps": round(
                2 * gb_ref * 3 / 4 / exposed / 1e9, 2),
        }
    return out


_SWEEP = {
    # headline (reference parity) model: sweep to find the throughput knee
    "pyramidnet": (64, 256, 1024),
    # north-star model (BASELINE.json): ImageNet shapes
    "resnet50": (64, 256),
    # long-context causal LM (flash attention) at seq 4096: 'small' is the
    # throughput row (1.1M tok/s), 'base'/'large' the MFU rows (d_model
    # 512/1024 feed the MXU properly — see LM_ROOFLINE.md; 'large' is the
    # roofline-cash row: 239M params at bs 4, no remat, dense head — the
    # measured-best config, see bench_lm's docstring)
    "lm": (8,),
}

_LM_SIZES = ("small", "base", "large", "base-moe8")
# per-size batch override for the sweep (explicit --batch-size wins):
# 'large' peaks at bs 4 — see bench_lm's docstring
_LM_BS = {"large": 4}


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="all",
                   choices=["all", "pyramidnet", "resnet50", "lm"])
    p.add_argument("--batch-size", type=int, default=0,
                   help="single batch size instead of the sweep")
    p.add_argument("--quick", action="store_true",
                   help="single config only (default pyramidnet bs=64; "
                        "honors explicit --model / --batch-size)")
    p.add_argument("--sample-budget", type=int, default=0,
                   help="override the per-config timed sample budget "
                        "(smoke tests on slow hosts; 0 = default)")
    p.add_argument("--records-file", default="bench_records.json",
                   help="where the full per-config records + scaling model "
                        "are written (the final stdout line stays compact)")
    p.add_argument("--lm-size", default="all",
                   choices=["all"] + list(_LM_SIZES),
                   help="restrict the LM rows to one size")
    p.add_argument("--skip-host-overhead", action="store_true",
                   help="skip the sync/async/unrolled host-overhead "
                        "microbench row")
    p.add_argument("--skip-serving", action="store_true",
                   help="skip the serving (prefill/decode tokens/sec vs "
                        "batch size) row")
    p.add_argument("--skip-fleet", action="store_true",
                   help="skip the serving-fleet row (1 vs 2 replica "
                        "Router throughput + kill-one-replica failover "
                        "drill)")
    p.add_argument("--skip-chunked", action="store_true",
                   help="skip the chunked-prefill interference row "
                        "(p99 inter-token latency with/without "
                        "chunking under mixed long-prompt traffic + "
                        "the disaggregated-fleet handoff receipt)")
    p.add_argument("--skip-kv-hierarchy", action="store_true",
                   help="skip the hierarchical KV cache row "
                        "(cold/HBM/host/disk TTFT per tier + the "
                        "fleet prefix-directory kill drill)")
    p.add_argument("--skip-observability", action="store_true",
                   help="skip the observability-overhead (tracer on vs "
                        "off steps/sec) row")
    p.add_argument("--skip-multitenant", action="store_true",
                   help="skip the multi-tenant serving row (batched "
                        "multi-LoRA, grammar-constrained decode, token "
                        "streaming — round 22)")
    p.add_argument("--skip-elastic", action="store_true",
                   help="skip the elastic-training row (kill-one-of-N "
                        "MTTR drill + liveness-layer overhead)")
    p.add_argument("--skip-elastic-tcp", action="store_true",
                   help="skip the TCP-backed elastic row (the same "
                        "kill-one-of-N MTTR drill through a localhost "
                        "TCPStoreServer instead of the in-process "
                        "store)")
    p.add_argument("--skip-store-rpc", action="store_true",
                   help="skip the control-plane store RPC microbench "
                        "(local vs TCP per-verb latency)")
    p.add_argument("--skip-obs-pipeline", action="store_true",
                   help="skip the serve observability-pipeline row "
                        "(correlated tracing + exporter + SLO eval on "
                        "vs off decode tokens/sec)")
    p.add_argument("--skip-robustness", action="store_true",
                   help="skip the robustness (resil step guard on vs off "
                        "steps/sec) row")
    p.add_argument("--skip-audit", action="store_true",
                   help="skip the program-shape audit row (pinned "
                        "train/megatron/decode/verify collective census "
                        "+ donated bytes vs the checked-in baseline)")
    p.add_argument("--serve-size", default=None,
                   help="LM size for the serving row (default: tiny on "
                        "CPU, base on an accelerator)")
    p.add_argument("--skip-paged-kernel", action="store_true",
                   help="skip the isolated paged-attend microbench "
                        "(dense vs gather vs Pallas paged kernel)")
    p.add_argument("--skip-kernels", action="store_true",
                   help="skip the kernel microbench row (attention "
                        "old-vs-new fwd+bwd + sort vs sortless sampling)")
    p.add_argument("--kernel-seqs", default="4096",
                   help="comma-separated attention seq lengths for the "
                        "kernels row (default 4096; pass 4096,32768 on "
                        "a real TPU — 32k under the CPU interpreter "
                        "takes minutes per iteration)")
    p.add_argument("--kernel-iters", type=int, default=2,
                   help="timed iterations per kernels-row config")
    a = p.parse_args(argv)

    if a.quick:
        # --quick narrows to ONE config but respects explicit choices
        # (it used to silently override --model/--batch-size).
        model = a.model if a.model != "all" else "pyramidnet"
        configs = [(model, a.batch_size or _SWEEP[model][0])]
    elif a.batch_size:
        models = _SWEEP.keys() if a.model == "all" else [a.model]
        configs = [(m, a.batch_size) for m in models]
    else:
        models = _SWEEP.keys() if a.model == "all" else [a.model]
        configs = [(m, bs) for m in models for bs in _SWEEP[m]]

    kind = getattr(jax.devices()[0], "device_kind", jax.devices()[0].platform)
    peak = peak_flops_per_chip()
    print(f"device: {kind} x{jax.device_count()}  "
          f"peak_bf16: {peak / 1e12 if peak else float('nan'):.0f} TFLOP/s",
          file=sys.stderr, flush=True)

    records = []
    # --quick keeps its one-config contract: a single LM size, not the set
    if a.lm_size != "all":
        lm_sizes = (a.lm_size,)
    else:
        lm_sizes = (_LM_SIZES[:1] if a.quick else _LM_SIZES)
    for model_name, sweep_bs in configs:
        sizes = lm_sizes if model_name == "lm" else (None,)
        for size in sizes:
            bs = sweep_bs
            if model_name == "lm" and not a.batch_size:
                bs = _LM_BS.get(size, sweep_bs)
            try:
                if model_name == "lm":
                    # budget caps the timed LM iterations too (floor 3)
                    lm_iters = (max(3, a.sample_budget // bs)
                                if a.sample_budget else 30)
                    row = bench_lm(bs, size=size, iters=lm_iters)
                else:
                    row = bench_one(model_name, bs,
                                    sample_budget=a.sample_budget or None)
            except Exception as e:  # e.g. OOM at a large batch — record it
                row = {"model": model_name, "batch_size": bs,
                       "error": f"{type(e).__name__}: {e}"[:200]}
                if size:
                    row["size"] = size
            records.append(row)
            print("  " + json.dumps(row), file=sys.stderr, flush=True)

    host_row = None
    if not a.skip_host_overhead:
        # host-overhead receipt: sync-every-step vs async-drain vs unrolled
        # dispatch through the SAME train_epoch loop (tiny model, so the
        # loop's host↔device stalls dominate) — see SCALING.md
        try:
            host_row = bench_host_overhead(
                steps=max(48, a.sample_budget // 64) if a.sample_budget
                else 192)
        except Exception as e:   # the microbench must never sink the bench
            host_row = {"model": "host_overhead",
                        "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(host_row)
        print("  " + json.dumps(host_row), file=sys.stderr, flush=True)

    obs_row = None
    if not a.skip_observability:
        # observability-overhead receipt: tracer+sentinel+goodput on vs
        # off through the same async train_epoch (<2% contract, ISSUE 3)
        try:
            obs_row = bench_observability(
                steps=max(48, a.sample_budget // 64) if a.sample_budget
                else 192)
        except Exception as e:   # the obs row must never sink the bench
            obs_row = {"model": "observability",
                       "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(obs_row)
        print("  " + json.dumps(obs_row), file=sys.stderr, flush=True)

    obs_pipe_row = None
    if not a.skip_obs_pipeline:
        # serve observability-pipeline receipt: correlated tracing +
        # continuous exporter + SLO eval on vs off through the same
        # Scheduler traffic (<2% contract, ISSUE 11)
        try:
            obs_pipe_row = bench_obs_pipeline()
        except Exception as e:  # the obs row must never sink the bench
            obs_pipe_row = {"model": "obs_pipeline",
                            "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(obs_pipe_row)
        print("  " + json.dumps(obs_pipe_row), file=sys.stderr,
              flush=True)

    resil_row = None
    if not a.skip_robustness:
        # robustness receipt: the resil step guard folded into the
        # compiled step vs off through the same async train_epoch (<2%
        # contract, ISSUE 5 — same bar as the observability row)
        try:
            resil_row = bench_robustness(
                steps=max(24, a.sample_budget // 256) if a.sample_budget
                else 48)
        except Exception as e:   # the resil row must never sink the bench
            resil_row = {"model": "robustness",
                         "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(resil_row)
        print("  " + json.dumps(resil_row), file=sys.stderr, flush=True)

    audit_row = None
    if not a.skip_audit:
        # program-shape receipt (ISSUE 15): collective census + donated
        # bytes of the pinned programs, with named drift vs baseline
        try:
            audit_row = bench_audit()
        except Exception as e:  # the audit row must never sink the bench
            audit_row = {"model": "audit",
                         "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(audit_row)
        print("  " + json.dumps(audit_row), file=sys.stderr, flush=True)

    kern_row = None
    if not a.skip_kernels:
        # kernel-round receipt: attention fwd+bwd old (unfused rope,
        # hardcoded blocks) vs new (fused rope, autotune table) + the
        # decode sampling epilogue sorted vs sortless (ISSUE 8)
        try:
            kern_row = bench_kernels(
                seqs=tuple(int(s) for s in a.kernel_seqs.split(",")),
                iters=a.kernel_iters)
        except Exception as e:  # the kernels row must never sink the bench
            kern_row = {"model": "kernels",
                        "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(kern_row)
        print("  " + json.dumps(kern_row), file=sys.stderr, flush=True)

    pk_row = None
    if not a.skip_paged_kernel:
        # paged-attend microbench (kernel round 2): dense vs gather vs
        # the Pallas paged kernel at decode/verify widths, with the
        # HBM-bytes argument that is the TPU claim
        try:
            pk_row = bench_paged_kernel()
        except Exception as e:  # must never sink the bench
            pk_row = {"model": "paged_kernel",
                      "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(pk_row)
        print("  " + json.dumps(pk_row), file=sys.stderr, flush=True)

    serve_row = None
    if not a.skip_serving:
        # serving row: prefill vs decode tokens/sec vs batch size — the
        # first workload receipt of the serve/ subsystem (ISSUE 2)
        try:
            serve_row = bench_serving(size=a.serve_size)
        except Exception as e:  # the serving row must never sink the bench
            serve_row = {"model": "serving",
                         "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(serve_row)
        print("  " + json.dumps(serve_row), file=sys.stderr, flush=True)

    fleet_row = None
    if not a.skip_fleet:
        # fleet row: Router over thread-hosted replicas — 1 vs 2 replica
        # throughput + the kill-one-replica failover receipts (ISSUE 9)
        try:
            fleet_row = bench_fleet()
        except Exception as e:  # the fleet row must never sink the bench
            fleet_row = {"model": "fleet",
                         "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(fleet_row)
        print("  " + json.dumps(fleet_row), file=sys.stderr, flush=True)

    chunked_row = None
    if not a.skip_chunked:
        # chunked-prefill interference row (ISSUE 14): p99 inter-token
        # latency with/without chunking + the disagg handoff receipt
        try:
            chunked_row = bench_chunked_prefill()
        except Exception as e:  # the chunked row must never sink the bench
            chunked_row = {"model": "chunked_prefill",
                           "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(chunked_row)
        print("  " + json.dumps(chunked_row), file=sys.stderr, flush=True)

    kvh_row = None
    if not a.skip_kv_hierarchy:
        # hierarchical KV cache row (round 23): TTFT at every tier of
        # the spill hierarchy + the fleet prefix-directory kill drill
        try:
            kvh_row = bench_kv_hierarchy()
        except Exception as e:  # the kv row must never sink the bench
            kvh_row = {"model": "kv_hierarchy",
                       "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(kvh_row)
        print("  " + json.dumps(kvh_row), file=sys.stderr, flush=True)

    mt_row = None
    if not a.skip_multitenant:
        # multi-tenant row (round 22): N-adapter batching vs 1-adapter
        # vs base, grammar-constrained vs free decode, and the
        # streaming first-token gap
        try:
            mt_row = bench_multitenant()
        except Exception as e:  # must never sink the bench
            mt_row = {"model": "multitenant",
                      "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(mt_row)
        print("  " + json.dumps(mt_row), file=sys.stderr, flush=True)

    elastic_row = None
    if not a.skip_elastic:
        # elastic row: thread-hosted worker world — kill-one-of-N MTTR
        # decomposition + liveness-layer overhead receipt (ISSUE 12)
        try:
            elastic_row = bench_elastic()
        except Exception as e:  # the elastic row must never sink the bench
            elastic_row = {"model": "elastic",
                           "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(elastic_row)
        print("  " + json.dumps(elastic_row), file=sys.stderr, flush=True)

    elastic_tcp_row = None
    if not a.skip_elastic_tcp:
        # the SAME drill through real sockets (ISSUE 13): TCP-backed
        # MTTR beside the in-process row
        try:
            elastic_tcp_row = bench_elastic(backend="tcp")
        except Exception as e:  # must never sink the bench
            elastic_tcp_row = {"model": "elastic_tcp",
                               "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(elastic_tcp_row)
        print("  " + json.dumps(elastic_tcp_row), file=sys.stderr,
              flush=True)

    store_rpc_row = None
    if not a.skip_store_rpc:
        # store RPC microbench (ISSUE 13): local vs TCP verb latency
        try:
            store_rpc_row = bench_store_rpc()
        except Exception as e:  # must never sink the bench
            store_rpc_row = {"model": "store_rpc",
                             "error": f"{type(e).__name__}: {e}"[:200]}
        records.append(store_rpc_row)
        print("  " + json.dumps(store_rpc_row), file=sys.stderr,
              flush=True)

    ok = [r for r in records if "samples_per_sec" in r]
    # headline = the best-MFU row of the reference-parity model (pyramidnet),
    # so vs_baseline stays an apples-to-apples per-sample ratio against the
    # P100 PyramidNet number and the metric name is stable run-to-run; on
    # devices without an MFU estimate (CPU) the best-throughput row wins.
    # All rows, including the reference bs=64 config, stay in "records".
    pyr = [r for r in ok if r["model"] == "pyramidnet"] or ok
    head = (max(pyr, key=lambda r: (r.get("mfu", 0.0), r["samples_per_sec"]))
            if pyr else None)
    if head is None:
        # total failure: the per-config error rows still go to the records
        # file so the artifact says WHICH config failed and how
        fail = {"metric": "bench_failed", "value": 0,
                "unit": "samples/sec", "vs_baseline": 0}
        try:
            with open(a.records_file, "w") as f:
                json.dump({**fail, "records": records}, f, indent=1)
            fail["records_file"] = a.records_file
        except OSError as e:
            print(f"records file not written: {e}", file=sys.stderr)
        print(json.dumps(fail), flush=True)
        raise SystemExit(1)

    best = max(ok, key=lambda r: r["samples_per_sec"])
    names = {"pyramidnet": "pyramidnet110_cifar10",
             "resnet50": "resnet50_imagenet",
             "lm": f"lm_{head.get('size', 'small')}_seq{head.get('seq')}"}
    # summary = the compact scalars-only final stdout line; full = summary
    # plus the per-config records and the modeled scaling section, written
    # to --records-file and stderr (round 4 lost its bench artifact to a
    # truncated stdout line — the driver captures only a tail window)
    summary = {
        "metric": (f"{names[head['model']]}"
                   f"_train_samples_per_sec_bs{head['batch_size']}"),
        "value": head["samples_per_sec"],
        "unit": "samples/sec",
        # null (not 0.0) when no reference baseline applies to the headline
        # model, so consumers don't read "no baseline" as "0x regression"
        "vs_baseline": head.get("vs_baseline"),
        "device": kind,
    }
    if "mfu" in head:
        summary["mfu"] = head["mfu"]
    rn = [r for r in ok if r["model"] == "resnet50"]
    if rn:
        rbest = max(rn, key=lambda r: r["samples_per_sec"])
        summary["resnet50_samples_per_sec"] = rbest["samples_per_sec"]
        if "mfu" in rbest:
            summary["resnet50_mfu"] = rbest["mfu"]
    lm = [r for r in ok if r["model"] == "lm"]
    if lm:
        # throughput and MFU headline may come from different LM sizes
        # ('small' wins tokens/sec, 'base'/'large' win MFU) — report each
        lbest = max(lm, key=lambda r: r.get("tokens_per_sec", 0))
        summary["lm_tokens_per_sec"] = lbest.get("tokens_per_sec")
        with_mfu = [r for r in lm if "mfu" in r]
        if with_mfu:
            lm_mfu_best = max(with_mfu, key=lambda r: r["mfu"])
            summary["lm_mfu"] = lm_mfu_best["mfu"]
            summary["lm_mfu_size"] = lm_mfu_best.get("size")
    if host_row and "async_speedup_vs_sync" in host_row:
        summary["host_overhead_async_speedup"] = \
            host_row["async_speedup_vs_sync"]
    if obs_row and "overhead_frac" in obs_row:
        summary["observability_overhead_frac"] = obs_row["overhead_frac"]
    if obs_pipe_row and "overhead_frac" in obs_pipe_row:
        summary["obs_pipeline_overhead_frac"] = \
            obs_pipe_row["overhead_frac"]
        summary["obs_pipeline_tokens_per_sec"] = \
            obs_pipe_row["on_tokens_per_sec"]
        summary["obs_export_snapshots"] = \
            obs_pipe_row.get("export_snapshots")
        summary["slo_breach_events"] = \
            obs_pipe_row.get("slo_breach_events")
        summary["slo_burn_crossings"] = \
            obs_pipe_row.get("slo_burn_crossings")
    if resil_row and "overhead_frac" in resil_row:
        summary["robustness_overhead_frac"] = resil_row["overhead_frac"]
    if audit_row and "drift_findings" in audit_row:
        # program-shape drift: 0 = the compiled hot paths still match
        # the checked-in census baseline (collectives, donation, zero
        # host traffic) — the ISSUE 15 regression harness
        summary["audit_drift_findings"] = audit_row["drift_findings"]
        summary["audit_decode_host_transfers"] = \
            audit_row["serve_decode"]["host_transfers"]
        summary["audit_train_donated_bytes"] = \
            audit_row["train_step"]["donated_bytes"]
        summary["audit_train_allreduces"] = \
            audit_row["train_step"]["collectives_hlo"].get(
                "all-reduce", 0)
    if kern_row and kern_row.get("attention"):
        # kernel receipt: the largest-seq head_dim-128 entry is the one
        # the roofline story hangs on; fall back to whatever ran
        ka = kern_row["attention"]
        best_a = max(ka, key=lambda e: (e["head_dim"] == 128, e["seq"]))
        summary["kernel_attn_speedup"] = best_a["speedup"]
        summary["kernel_attn_tflops"] = best_a["new_tflops"]
        summary["kernel_attn_seq"] = best_a["seq"]
    if kern_row and kern_row.get("sampling"):
        ks = max(kern_row["sampling"], key=lambda e: e["vocab"])
        summary["sampling_sortless_speedup"] = ks["speedup"]
        summary["sampling_sortless_us"] = ks["sortless_us"]
        summary["sampling_vocab"] = ks["vocab"]
    if pk_row and pk_row.get("rows"):
        # paged-kernel receipt (kernel round 2): the decode-width row's
        # HBM-bytes ratio is the TPU claim; the ms columns are honest
        # but interpreter-bound on CPU (interpret flag says which)
        pk_d = next((r for r in pk_row["rows"] if r["s_new"] == 1),
                    pk_row["rows"][0])
        summary["kernel_paged_bytes_x"] = pk_d["bytes_x"]
        summary["kernel_paged_ms"] = pk_d["kernel_ms"]
        summary["kernel_paged_gather_ms"] = pk_d["gather_ms"]
        summary["kernel_paged_interpret"] = pk_row["interpret"]
    if serve_row and serve_row.get("sweep"):
        best_d = max(serve_row["sweep"],
                     key=lambda s: s["decode_tokens_per_sec"])
        summary["serve_decode_tokens_per_sec"] = \
            best_d["decode_tokens_per_sec"]
        summary["serve_prefill_tokens_per_sec"] = max(
            s["prefill_tokens_per_sec"] for s in serve_row["sweep"])
    if serve_row and serve_row.get("spec"):
        # spec-decode receipt: best greedy spec config vs the k=0
        # baseline through the same scheduler (ISSUE 4 acceptance)
        greedy = [e for e in serve_row["spec"] if e["temperature"] == 0.0]
        base = next((e for e in greedy if e["k"] == 0), None)
        spec = [e for e in greedy if e["k"] > 0]
        if base and spec:
            best_s = max(spec, key=lambda e: e["decode_tokens_per_sec"])
            summary["serve_spec_tokens_per_sec"] = \
                best_s["decode_tokens_per_sec"]
            summary["serve_spec_acceptance_rate"] = \
                best_s["acceptance_rate"]
            summary["serve_spec_speedup"] = round(
                best_s["decode_tokens_per_sec"]
                / base["decode_tokens_per_sec"], 3) \
                if base["decode_tokens_per_sec"] else None
    if serve_row and serve_row.get("paged"):
        # paged-arena receipt: prefix-cache hits measured on repeated-
        # system-prompt traffic, TTFT vs the dense row (ISSUE 6)
        rows = {e["arena"]: e for e in serve_row["paged"]}
        pp, dense = rows.get("paged+prefix"), rows.get("dense")
        if pp and dense:
            summary["serve_paged_tokens_per_sec"] = \
                pp["decode_tokens_per_sec"]
            summary["serve_prefix_hit_rate"] = pp["prefix_hit_rate"]
            summary["serve_prefix_ttft_vs_dense"] = round(
                pp["ttft_s_mean"] / dense["ttft_s_mean"], 3) \
                if dense["ttft_s_mean"] else None
    if serve_row and serve_row.get("quant"):
        # quantization receipt (ISSUE 7): measured tokens/sec per config
        # plus the byte receipts that ARE the TPU speedup (decode is
        # HBM-BW-bound; CPU timings here pay the dequant as compute)
        rows = {(e["arena"], e["weights"]): e
                for e in serve_row["quant"]}
        f32d, w8kv8d = rows.get(("dense", "f32")), \
            rows.get(("dense", "w8kv8"))
        if f32d and w8kv8d:
            summary["serve_quant_tokens_per_sec"] = \
                w8kv8d["decode_tokens_per_sec"]
            summary["serve_quant_speedup_vs_f32"] = round(
                w8kv8d["decode_tokens_per_sec"]
                / f32d["decode_tokens_per_sec"], 3) \
                if f32d["decode_tokens_per_sec"] else None
            summary["serve_quant_bytes_per_token"] = \
                w8kv8d["bytes_per_token"]
            summary["serve_quant_bytes_per_token_f32"] = \
                f32d["bytes_per_token"]
            summary["serve_quant_param_bytes_ratio"] = round(
                f32d["param_bytes"] / w8kv8d["param_bytes"], 3)
            summary["serve_quant_kv_arena_ratio"] = round(
                f32d["kv_arena_bytes"] / w8kv8d["kv_arena_bytes"], 3)
        f32p, w8kv8p = rows.get(("paged", "f32")), \
            rows.get(("paged", "w8kv8"))
        if f32p and w8kv8p and f32p["n_pages"]:
            summary["serve_quant_paged_capacity_x"] = round(
                w8kv8p["n_pages"] / f32p["n_pages"], 3)
        # fp8 receipt (kernel round 2): bytes/token strictly below the
        # int8 row — one-byte payloads with bf16 (not f32) scale
        # sidecars and fp8 weight matmuls
        fp8d = rows.get(("dense", "w8fkvf8"))
        if fp8d and w8kv8d:
            summary["serve_fp8_tokens_per_sec"] = \
                fp8d["decode_tokens_per_sec"]
            summary["serve_fp8_bytes_per_token"] = \
                fp8d["bytes_per_token"]
            summary["serve_fp8_vs_int8_bytes_x"] = round(
                w8kv8d["bytes_per_token"] / fp8d["bytes_per_token"], 3) \
                if fp8d["bytes_per_token"] else None
        fp8p = rows.get(("paged", "w8fkvf8"))
        if fp8p and f32p and f32p["n_pages"]:
            summary["serve_fp8_paged_capacity_x"] = round(
                fp8p["n_pages"] / f32p["n_pages"], 3)
    if fleet_row and fleet_row.get("replicas"):
        # fleet receipt (ISSUE 9): per-replica-count throughput plus
        # the failover drill — requests_lost MUST report 0
        by_n = {e["n_replicas"]: e for e in fleet_row["replicas"]}
        if 1 in by_n:
            summary["fleet_tokens_per_sec_1r"] = \
                by_n[1]["decode_tokens_per_sec"]
        if 2 in by_n:
            summary["fleet_tokens_per_sec_2r"] = \
                by_n[2]["decode_tokens_per_sec"]
        if 1 in by_n and 2 in by_n and by_n[1]["decode_tokens_per_sec"]:
            summary["fleet_speedup_2r"] = round(
                by_n[2]["decode_tokens_per_sec"]
                / by_n[1]["decode_tokens_per_sec"], 3)
        fo = fleet_row.get("failover") or {}
        summary["fleet_time_to_evict_s"] = fo.get("time_to_evict_s")
        summary["fleet_requests_retried"] = fo.get("requests_retried")
        summary["fleet_requests_lost"] = fo.get("requests_lost")

    if chunked_row and "error" not in chunked_row:
        # chunked-prefill receipt (ISSUE 14): the decoders' inter-token
        # p99 with a whole-prompt prefill landing mid-run vs the same
        # traffic chunked, token-identity asserted; plus the
        # disaggregated-fleet migration receipt
        summary["serve_chunked_p99_tok_latency_s"] = \
            chunked_row["chunked"]["p99_tok_latency_s"]
        summary["serve_chunked_p99_whole_s"] = \
            chunked_row["whole"]["p99_tok_latency_s"]
        summary["serve_chunked_p99_improvement_x"] = \
            chunked_row["p99_improvement_x"]
        summary["serve_chunked_token_identical"] = \
            chunked_row["token_identical"]
        dis = chunked_row.get("disagg") or {}
        summary["fleet_disagg_token_identical"] = \
            dis.get("token_identical")
        summary["fleet_disagg_migrations"] = dis.get("migrations")
        summary["fleet_disagg_kv_handoff_pages"] = \
            dis.get("kv_handoff_pages")
        summary["fleet_disagg_kv_handoff_s_mean"] = \
            dis.get("kv_handoff_s_mean")

    if mt_row and "error" not in mt_row:
        # multi-tenant receipts (round 22): N-adapter batching keeps
        # throughput (the non-split-batch claim), constrained decode's
        # host-mask tax, and the streamed-first-token gap
        lo, gr, st = mt_row["lora"], mt_row["grammar"], mt_row["stream"]
        summary["serve_lora_tokens_per_sec"] = \
            lo["n_adapters_tokens_per_sec"]
        summary["serve_lora_vs_base"] = round(
            lo["n_adapters_tokens_per_sec"] / lo["base_tokens_per_sec"],
            3) if lo["base_tokens_per_sec"] else None
        summary["serve_lora_vs_one_adapter"] = round(
            lo["n_adapters_tokens_per_sec"]
            / lo["one_adapter_tokens_per_sec"], 3) \
            if lo["one_adapter_tokens_per_sec"] else None
        summary["serve_grammar_tokens_per_sec"] = \
            gr["constrained_tokens_per_sec"]
        summary["serve_grammar_vs_free"] = round(
            gr["constrained_tokens_per_sec"] / gr["free_tokens_per_sec"],
            3) if gr["free_tokens_per_sec"] else None
        summary["serve_stream_ttfst_s"] = st["ttfst_s_mean"]
        summary["serve_stream_ttft_s"] = st["ttft_s_mean"]

    if elastic_row and "error" not in elastic_row:
        dr = elastic_row.get("drill") or {}
        summary["elastic_detect_s"] = dr.get("detect_s")
        summary["elastic_reform_s"] = dr.get("reform_s")
        summary["elastic_restore_s"] = dr.get("restore_s")
        summary["elastic_mttr_s"] = dr.get("mttr_s")
        summary["elastic_samples_lost"] = dr.get("samples_lost")
        summary["elastic_samples_double_counted"] = \
            dr.get("samples_double_counted")
        lv = elastic_row.get("liveness") or {}
        summary["elastic_liveness_overhead_frac"] = \
            lv.get("overhead_frac")

    if elastic_tcp_row and "error" not in elastic_tcp_row:
        dr = elastic_tcp_row.get("drill") or {}
        summary["elastic_tcp_detect_s"] = dr.get("detect_s")
        summary["elastic_tcp_reform_s"] = dr.get("reform_s")
        summary["elastic_tcp_restore_s"] = dr.get("restore_s")
        summary["elastic_tcp_mttr_s"] = dr.get("mttr_s")
        summary["elastic_tcp_samples_lost"] = dr.get("samples_lost")
        summary["elastic_tcp_samples_double_counted"] = \
            dr.get("samples_double_counted")

    if store_rpc_row and "error" not in store_rpc_row:
        for backend in ("local", "tcp"):
            verbs = store_rpc_row.get(backend) or {}
            get = verbs.get("get") or {}
            summary[f"store_rpc_{backend}_get_p50_us"] = get.get("p50")
            summary[f"store_rpc_{backend}_get_p99_us"] = get.get("p99")

    full = dict(summary)
    full["records"] = records
    full["best"] = {"model": best["model"], "batch_size": best["batch_size"],
                    "samples_per_sec": best["samples_per_sec"]}
    try:
        scaling = scaling_section(ok)
        if scaling:
            full["scaling"] = scaling
    except Exception as e:   # modeled section must never sink the bench
        print(f"scaling section failed: {e}", file=sys.stderr)
    try:
        with open(a.records_file, "w") as f:
            json.dump(full, f, indent=1)
        summary["records_file"] = a.records_file
    except OSError as e:     # unwritable cwd must never sink the bench
        print(f"records file not written: {e}", file=sys.stderr)
    print("full result: " + json.dumps(full), file=sys.stderr, flush=True)

    print(json.dumps(summary), flush=True)
    return full


if __name__ == "__main__":
    main()
