#!/usr/bin/env python
"""MNIST CNN under single-host multi-device data parallelism, fit() API.

Capability parity with reference tensorflow2/mnist_mirror_strategy.py:
``MirroredStrategy`` becomes a `DataParallel` strategy over the local mesh;
model build + compile happen against the strategy object (the reference does
it inside ``strategy.scope()``, :68-73 — JAX needs no context manager: the
strategy places parameters when they are created).

    python examples/mnist_mirror_strategy.py --batch_size 64 --epochs 2
"""

from common import bootstrap
from dtdl_tpu.parallel import data_parallel_local
from dtdl_tpu.utils.config import add_data_flags, make_parser

from mnist_single import add_tf2_flags, run


def main():
    parser = make_parser("dtdl_tpu: Keras-style MNIST CNN (MirroredStrategy)")
    add_tf2_flags(parser)
    add_data_flags(parser, dataset="mnist")
    args = parser.parse_args()
    bootstrap(args)
    strategy = data_parallel_local()  # all local devices, like MirroredStrategy
    print(f"Mirrored DP over {strategy.num_replicas} local device(s)",
          flush=True)
    run(args, strategy)


if __name__ == "__main__":
    main()
