#!/usr/bin/env python
"""Continuous-batching LM serving — the request path of the north star.

The reference repos train and stop; this example closes the loop the
ROADMAP asks for ("serves heavy traffic"): a TransformerLM — freshly
initialized, restored from a training snapshot, or bridged from a
4D-megatron run — behind the dtdl_tpu.serve engine+scheduler.  Mixed
prompt lengths and mixed sampling configs share one fixed-shape decode
program; requests are admitted into KV-arena slots the moment one frees.

    python examples/serve_lm.py                       # synthetic traffic
    python examples/serve_lm.py --n-requests 32 --n-slots 8 \
        --temperature 0.8 --top-p 0.95
    python examples/serve_lm.py --restore ckpt.msgpack --model-size small
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

from common import bootstrap
from dtdl_tpu.models import transformer_lm
from dtdl_tpu.serve import InferenceEngine, Request, SampleParams, Scheduler
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import flag, make_parser


def main():
    parser = make_parser("dtdl_tpu: batched LM serving")
    flag(parser, "--model-size", default="tiny",
         choices=["tiny", "small", "base"])
    flag(parser, "--restore", default="",
         help="msgpack weights to serve (default: random init)")
    flag(parser, "--n-slots", type=int, default=4,
         help="decode batch width (KV-arena rows)")
    flag(parser, "--n-requests", type=int, default=12)
    flag(parser, "--max-new-tokens", type=int, default=24)
    flag(parser, "--temperature", type=float, default=0.0,
         help="0 = greedy")
    flag(parser, "--top-k", type=int, default=0, help="0 = disabled")
    flag(parser, "--top-p", type=float, default=1.0, help="1 = disabled")
    flag(parser, "--harvest-lag", type=int, default=4,
         help="steps a sampled token may stay device-side before the "
              "host reads it (0 = sync every step)")
    flag(parser, "--speculate", type=int, default=0,
         help="speculative decoding: max drafted tokens per step "
              "(0 = off; lossless — greedy output is token-identical)")
    flag(parser, "--draft", default="ngram", choices=["ngram", "model"],
         help="draft source for --speculate: device-free n-gram prompt "
              "lookup, or a small draft transformer sharing the vocab")
    flag(parser, "--page-size", type=int, default=0,
         help="block-paged KV arena: tokens per page (0 = dense "
              "per-slot rows; must divide max_seq)")
    flag(parser, "--n-pages", type=int, default=0,
         help="page-pool size for --page-size (0 = dense-equivalent "
              "capacity; smaller overcommits HBM, admission then gates "
              "on free pages)")
    import argparse
    flag(parser, "--prefix-cache", action=argparse.BooleanOptionalAction,
         default=True,
         help="cross-request prefix caching over full prompt pages "
              "(paged arena only): identical prompt prefixes prefill "
              "once and are shared read-only")
    flag(parser, "--shared-prefix", type=int, default=0,
         help="synthetic traffic: give every request this many common "
              "leading tokens (a system prompt) so the prefix cache "
              "has something to hit")
    flag(parser, "--spill-host-mb", type=int, default=0,
         help="hierarchical KV cache (round 23): host-DRAM spill store "
              "byte budget in MiB (0 = off); evicted refcount-0 cached "
              "pages spill instead of freeing and a prefix miss "
              "restores them (needs --page-size + --prefix-cache)")
    flag(parser, "--spill-dir", default="",
         help="disk spill tier for --spill-host-mb: directory for the "
              "checksummed mmap'd spill file (host overflow demotes "
              "there; corrupt entries quarantine and recompute)")
    flag(parser, "--spill-disk-mb", type=int, default=256,
         help="disk spill file byte budget in MiB for --spill-dir")
    flag(parser, "--chunk-tokens", type=int, default=0,
         help="chunked prefill: per-step prompt token budget (0 = "
              "whole-prompt prefill); long admissions stop stalling "
              "in-flight decodes — greedy output stays token-identical")
    flag(parser, "--quantize", default="none",
         choices=["none", "w8", "w8kv8"],
         help="int8 serving (dtdl_tpu/quant): w8 = weight-only int8 "
              "matmuls, w8kv8 = + int8 KV arena; same compiled "
              "programs, ~4x less parameter HBM traffic")
    flag(parser, "--lora", default="",
         help="multi-tenant LoRA: comma-separated adapter checkpoint "
              "paths; requests round-robin over base + adapters, all "
              "batched through the SAME compiled steps (a missing path "
              "gets a random demo adapter saved there)")
    flag(parser, "--lora-rank", type=int, default=8,
         help="adapter rank for --lora (must match saved adapters)")
    flag(parser, "--json-schema", default="",
         help="grammar-constrained decoding: a JSON-schema file; every "
              "request's output is masked to valid JSON for it "
              "(vocab must cover ASCII, i.e. >= 128)")
    flag(parser, "--stream", action="store_true",
         help="attach a TokenStream per request and echo the first "
              "requests' tokens as the lag-harvest windows deliver them")
    flag(parser, "--seed", type=int, default=0)
    flag(parser, "--trace", default="",
         help="write a Chrome-trace-event JSON (Perfetto-loadable) of "
              "the scheduler phases (admit/dispatch/harvest) to this "
              "path")
    args = parser.parse_args()
    bootstrap(args)
    seed_everything(args.seed)

    model = transformer_lm(args.model_size, attn_impl="dense",
                           dtype=jnp.float32)
    example = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), example)["params"]
    import flax.linen as nn
    params = nn.unbox(params)
    if args.restore:
        from dtdl_tpu.ckpt import load_weights
        params = load_weights(args.restore, params)

    lora_paths = [p for p in args.lora.split(",") if p]
    for p in lora_paths:
        # out-of-the-box demo: synthesize (and persist) an adapter for
        # any path that doesn't exist yet
        import os
        if not os.path.exists(p):
            from dtdl_tpu.ckpt import save_weights
            from dtdl_tpu.serve import adapter_template
            tpl = adapter_template(params, rank=args.lora_rank)
            arng = np.random.default_rng(hash(p) % (2 ** 31))
            save_weights(p, jax.tree_util.tree_map(
                lambda x: np.asarray(arng.normal(0, 0.02, x.shape),
                                     np.float32), tpl))
            print(f"  --lora: saved demo adapter to {p}")

    from dtdl_tpu.obs import Observer
    obs = Observer(trace_path=args.trace or None, sentinel="warn")
    engine = InferenceEngine(model, params, n_slots=args.n_slots,
                             observer=obs, page_size=args.page_size,
                             n_pages=args.n_pages or None,
                             quantize_weights=args.quantize != "none",
                             kv_dtype=("int8" if args.quantize == "w8kv8"
                                       else None),
                             lora_rank=(args.lora_rank if lora_paths
                                        else 0),
                             lora_adapters=(len(lora_paths) + 1
                                            if lora_paths else 0))
    draft = None
    if args.speculate and args.draft == "model":
        # demo draft transformer: a narrower random-init LM sharing the
        # vocab (real deployments restore trained draft weights)
        from dtdl_tpu.serve import ModelDraft
        dm = transformer_lm("tiny", vocab_size=model.vocab_size,
                            attn_impl="dense", dtype=jnp.float32)
        dp = nn.unbox(dm.init(jax.random.PRNGKey(args.seed + 1),
                              example)["params"])
        # warmup pre-compiles the (ctx-bucket, k-bucket) generate
        # family NOW so the first request doesn't eat the compile
        draft = ModelDraft(dm, dp, warmup=args.speculate)
    sched = Scheduler(engine, seed=args.seed,
                      harvest_lag=args.harvest_lag, observer=obs,
                      draft=draft, prefix_cache=args.prefix_cache,
                      chunk_tokens=args.chunk_tokens or None,
                      spill_host_bytes=args.spill_host_mb << 20 or None,
                      spill_dir=args.spill_dir or None,
                      spill_disk_bytes=(args.spill_disk_mb << 20
                                        if args.spill_dir else None))
    sp = SampleParams(temperature=args.temperature, top_k=args.top_k,
                      top_p=args.top_p)

    # synthetic traffic: mixed prompt lengths, one shared sampling
    # config; --shared-prefix prepends a common "system prompt" so the
    # paged arena's prefix cache has repeated leading pages to hit
    rng = np.random.default_rng(args.seed)
    hi = min(64, model.max_seq // 2)
    if not 0 <= args.shared_prefix <= model.max_seq - hi - 1:
        parser.error(f"--shared-prefix must be in [0, "
                     f"{model.max_seq - hi - 1}] for this model")
    common = rng.integers(0, model.vocab_size,
                          args.shared_prefix).tolist()
    lens = rng.integers(4, hi, args.n_requests)

    dfa = None
    eos = None
    if args.json_schema:
        import json as _json
        if model.vocab_size < 128:
            parser.error("--json-schema needs a vocab covering ASCII "
                         f"(>= 128); this model has {model.vocab_size}")
        from dtdl_tpu.serve import byte_vocab, compile_json_schema
        with open(args.json_schema) as f:
            schema = _json.load(f)
        eos = model.vocab_size - 1
        dfa = compile_json_schema(schema, byte_vocab(model.vocab_size),
                                  eos_id=eos)
        print(f"  --json-schema: {dfa.n_states}-state token DFA "
              f"({dfa.nbytes():,} bytes of masks)")

    def mk_stream(i):
        if not args.stream:
            return None
        from dtdl_tpu.serve import TokenStream
        if i >= 2:              # echo only the first requests
            return TokenStream()
        return TokenStream(callback=lambda new, i=i: print(
            f"    stream req {i}: +{new}"))

    # round-robin tenants: base, then each --lora adapter in turn
    tenants = [None] + lora_paths
    reqs = [Request(common + rng.integers(0, model.vocab_size,
                                          n).tolist(),
                    args.max_new_tokens, sampling=sp,
                    speculate=args.speculate,
                    adapter=tenants[i % len(tenants)],
                    grammar=dfa, eos_id=(eos if dfa is not None
                                         else None),
                    stream=mk_stream(i))
            for i, n in enumerate(lens)]

    t0 = time.perf_counter()
    sched.run(reqs)
    dt = time.perf_counter() - t0
    for r in reqs[:4]:
        print(f"  req {r.rid}: prompt[{len(r.prompt)}] -> "
              f"{r.tokens[:12]}{'...' if len(r.tokens) > 12 else ''}")
    s = sched.metrics.summary()
    print(f"served {s['requests_finished']} requests in {dt:.2f}s  "
          f"(decode {s['decode_tokens_per_sec']} tok/s, occupancy "
          f"{s['occupancy_mean']:.0%}, ttft {s['ttft_s_mean'] * 1e3:.1f}ms)")
    if "ttft_s_p50" in s:
        print(f"  ttft p50/p95/p99: {s['ttft_s_p50'] * 1e3:.1f} / "
              f"{s['ttft_s_p95'] * 1e3:.1f} / {s['ttft_s_p99'] * 1e3:.1f} ms"
              f"   per-token p50/p99: "
              f"{s.get('tok_latency_s_p50', 0.0) * 1e3:.2f} / "
              f"{s.get('tok_latency_s_p99', 0.0) * 1e3:.2f} ms")
    if args.page_size:
        # the paged-arena receipts: how much prefill the prefix cache
        # skipped, and how many pool pages live traffic ever pinned
        print(f"  paged kv (page_size={args.page_size}): prefix hit "
              f"rate {s['prefix_hit_rate']:.0%}  prefill tokens saved "
              f"{s['prefill_tokens_saved']}  pages in use "
              f"{s['pages_in_use_last']}/{s['page_capacity']} "
              f"(peak {s['pages_in_use_peak']})  shed "
              f"{s['requests_shed']}")
    if args.spill_host_mb:
        # the hierarchy receipts: pages that left HBM and came back
        # instead of being recomputed, split by the tier that hit
        print(f"  kv spill: spilled {s['pages_spilled']} pages "
              f"({s['spill_bytes'] >> 10} KiB)  restored "
              f"{s['pages_restored']} (host {s['spill_host_hits']} / "
              f"disk {s['spill_disk_hits']} hits, "
              f"{s['restore_s'] * 1e3:.1f}ms)  quarantined "
              f"{s['spill_quarantined']}")
    if args.quantize != "none":
        # the quantization receipts: decode bytes/token (the TPU
        # roofline numerator), KV capacity gained at fixed HBM, and the
        # measured logits drift of int8 rounding on a probe prompt
        q = engine.compile_stats()["quant"]
        ref = InferenceEngine(model, params, n_slots=args.n_slots,
                              page_size=args.page_size,
                              n_pages=args.n_pages or None)
        rq = ref.compile_stats()["quant"]
        kv_x = (rq["kv_arena_bytes"] / q["kv_arena_bytes"]
                if q["kv_arena_bytes"] else 1.0)
        probe = jnp.asarray([reqs[0].prompt], jnp.int32)
        lf = model.apply({"params": params}, probe)
        lq = engine.model.apply({"params": engine.params}, probe)
        drift = float(jnp.max(jnp.abs(lf - lq))) \
            / max(float(jnp.max(jnp.abs(lf))), 1e-9)
        print(f"  quantized ({args.quantize}): decode bytes/token "
              f"{q['decode_hbm_bytes_per_token']:,} (f32: "
              f"{rq['decode_hbm_bytes_per_token']:,})  param bytes "
              f"{q['param_bytes']:,} ({rq['param_bytes']:,} f32)  "
              f"kv capacity x{kv_x:.2f} at fixed HBM "
              f"(~{int(args.n_slots * kv_x)} slots for these "
              f"{args.n_slots})  probe logits drift {drift:.1%}")
    if args.speculate:
        # per-request ACCEPTED tokens/sec (delivered tokens over the
        # request's own decode window) — the user-visible spec win
        rates = sorted((len(r.tokens) - 1) / (r.t_done - r.t_first)
                       for r in reqs
                       if len(r.tokens) > 1 and r.t_done > r.t_first)
        pct = (lambda p: rates[min(len(rates) - 1,
                                   int(p * (len(rates) - 1)))]) \
            if rates else (lambda p: 0.0)
        print(f"  speculative k<={args.speculate} ({args.draft}): "
              f"acceptance {s['spec_acceptance_rate']:.0%}  "
              f"tokens/step {s['tokens_per_step_mean']:.2f}  "
              f"accepted-tok/s p50/p95: {pct(0.5):.1f} / {pct(0.95):.1f}  "
              f"draft overhead {s['draft_s'] * 1e3:.1f}ms")
    if lora_paths:
        # the multi-tenant receipts: per-adapter delivered tokens, all
        # through ONE decode program (adapter ids are data)
        by = s["tokens_by_adapter"]
        mix = "  ".join(f"{k.rsplit('/', 1)[-1]}={v}"
                        for k, v in sorted(by.items()))
        print(f"  multi-lora ({len(lora_paths)} adapters, rank "
              f"{args.lora_rank}): tokens by tenant: {mix}  bank loads "
              f"{engine.adapter_bank.n_loads} evictions "
              f"{engine.adapter_bank.n_evictions}")
    if dfa is not None:
        ok = sum(1 for r in reqs if r.error is None)
        print(f"  constrained ({args.json_schema}): {ok}/{len(reqs)} "
              f"requests completed valid JSON; illegal draft tokens "
              f"trimmed {s['grammar_rejected_tokens']}")
        for r in reqs[:2]:
            body = r.tokens[:-1] if r.tokens and r.tokens[-1] == eos \
                else r.tokens
            print(f"    req {r.rid}: "
                  f"{''.join(chr(t) for t in body)!r}")
    if args.stream:
        print(f"  streaming: {s['stream_deliveries']} incremental "
              f"deliveries across {len(reqs)} requests")
    print("compiled programs:", engine.compile_stats())
    if args.trace:
        print(f"trace written to {obs.save()}", flush=True)


if __name__ == "__main__":
    main()
