#!/usr/bin/env python
"""MNIST with the Estimator API — the TF1-idiom entry point.

The reference's tensorflow/ (TF1) track is an empty placeholder (reference
tensorflow/README.md is zero-byte; declared at README.md:4-20); TF1's
canonical surface is ``model_fn`` / ``input_fn`` / ``RunConfig`` /
``train_and_evaluate``.  Flag spellings follow the TF1 convention
(underscores: --model_dir, --train_steps), both spellings accepted.

    python examples/tf_estimator.py --train_steps 600 --model_dir ./est
    # resumable by construction: rerun the same command to continue.
    # DDP over all local chips:
    python examples/tf_estimator.py --strategy ddp --batch_size 256
"""

import jax.numpy as jnp
import optax

from common import bootstrap, mnist_arrays, per_process_loader
from dtdl_tpu.models import MnistCNN
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import (Estimator, EstimatorSpec, EvalSpec, ModeKeys,
                            RunConfig, TrainSpec, train_and_evaluate)
from dtdl_tpu.utils.config import (add_data_flags, add_topology_flags, flag,
                                   make_parser)


def model_fn(mode, params):
    """Per-mode spec: same CNN for all modes; optimizer only for TRAIN."""
    model = MnistCNN(dtype=jnp.bfloat16 if params.get("bf16") else jnp.float32)
    tx = optax.adam(params.get("learning_rate", 1e-3)) \
        if mode == ModeKeys.TRAIN else None
    return EstimatorSpec(mode=mode, model=model, tx=tx)


def main():
    parser = make_parser("dtdl_tpu: TF1 Estimator-style MNIST")
    flag(parser, "--model_dir", default="./estimator_model")
    flag(parser, "--train_steps", type=int, default=600)
    flag(parser, "--eval_steps", type=int, default=0,
         help="eval batches per evaluation (0 = full test set)")
    flag(parser, "--batch_size", type=int, default=128)
    flag(parser, "--learning_rate", type=float, default=1e-3)
    flag(parser, "--save_checkpoints_steps", type=int, default=200)
    flag(parser, "--strategy", default="single",
         choices=["single", "dp", "ddp", "auto"])
    add_data_flags(parser, dataset="mnist")
    add_topology_flags(parser)
    args = parser.parse_args()
    bootstrap(args)

    (x, y), (vx, vy) = mnist_arrays(args)

    def train_input_fn():
        return per_process_loader(x, y, args.batch_size, shuffle=True, seed=0)

    def eval_input_fn():
        return per_process_loader(vx, vy, args.batch_size, shuffle=False,
                                  seed=0, drop_last=False)

    estimator = Estimator(
        model_fn, model_dir=args.model_dir,
        config=RunConfig(save_checkpoints_steps=args.save_checkpoints_steps,
                         log_step_count_steps=100),
        params={"learning_rate": args.learning_rate},
        strategy=choose_strategy(args.strategy))
    result = train_and_evaluate(
        estimator,
        TrainSpec(train_input_fn, max_steps=args.train_steps),
        EvalSpec(eval_input_fn, steps=args.eval_steps or None))
    print("final eval:", {k: round(float(v), 4) for k, v in result.items()},
          flush=True)

    # predict a few examples (TF1 predict generator shape)
    import itertools
    preds = list(itertools.islice(estimator.predict(eval_input_fn), 5))
    print("predictions:", [p["class_ids"] for p in preds],
          "labels:", list(vy[:5]), flush=True)


if __name__ == "__main__":
    main()
