#!/usr/bin/env python
"""MNIST MLP across processes/hosts, Trainer API (ChainerMN parity).

Capability parity with reference chainer/train_mnist_multi.py: the MPI
communicator (``pure_nccl``/``naive``, reference :49-62) becomes
`jax.distributed.initialize` + a global mesh; the multi-node optimizer's
gradient allreduce (reference :81-83) is the strategy's `lax.pmean`; rank-0
dataset load + ``scatter_dataset`` (reference :87-92) becomes deterministic
per-host sharding (every host reads its stripe — same partition, no wire
transfer); the multi-node evaluator (reference :101-104) is the psum'd eval
step; logging extensions are leader-gated (reference :108-114).

    python -m dtdl_tpu.launch.local --nproc 2 --devices-per-proc 2 -- \
        examples/train_mnist_multi.py -b 400 -e 2 --dataset-dir ./datasets
"""

import jax

from common import bootstrap
from dtdl_tpu.parallel import distributed_data_parallel
from dtdl_tpu.runtime import is_leader
from dtdl_tpu.utils.config import (add_data_flags, add_topology_flags, flag,
                                   make_parser)

from train_mnist import add_chainer_flags, build_trainer


def main():
    parser = make_parser("dtdl_tpu: Trainer-style MNIST MLP, multi-process DP")
    add_chainer_flags(parser, batchsize=400)
    add_data_flags(parser, dataset="mnist")
    add_topology_flags(parser)
    flag(parser, "--communicator", type=str, default="ici",
         help="accepted for parity (reference picks pure_nccl/naive, "
              "train_mnist_multi.py:49-62); XLA collectives are the only "
              "backend here")
    flag(parser, "--gpu", "-g", action="store_true",
         help="accepted for parity; JAX owns device selection")
    args = parser.parse_args()
    bootstrap(args)  # communicator creation ≙ rendezvous

    if is_leader():
        # rank-0 banner (reference chainer/train_mnist_multi.py:64-73)
        print("==========================================")
        print(f"Num process (COMM_WORLD): {jax.process_count()}")
        print(f"Using {jax.devices()[0].device_kind} "
              f"(communicator='{args.communicator}' -> XLA/ICI)")
        print(f"Num unit: {args.unit}")
        print(f"Num Minibatch-size: {args.batchsize}")
        print(f"Num epoch: {args.epoch}")
        print("==========================================", flush=True)

    strategy = distributed_data_parallel()
    trainer = build_trainer(args, strategy)
    if args.resume:
        trainer.resume(args.resume)
    trainer.run()


if __name__ == "__main__":
    main()
