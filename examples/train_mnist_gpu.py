#!/usr/bin/env python
"""MNIST MLP on all local devices, Trainer API (ParallelUpdater parity).

Capability parity with reference chainer/train_mnist_gpu.py: single-process
multi-device data parallelism driven by the Trainer.  Chainer's
``ParallelUpdater`` with a ``{'main': 0, 'second': 1}`` device map (reference
:87-93) becomes a `DataParallel` strategy over the local mesh — the device
map is the mesh.

    python examples/train_mnist_gpu.py -b 400 -e 3
"""

from common import bootstrap
from dtdl_tpu.parallel import data_parallel_local
from dtdl_tpu.utils.config import add_data_flags, flag, make_parser

from train_mnist import add_chainer_flags, build_trainer


def main():
    parser = make_parser("dtdl_tpu: Trainer-style MNIST MLP, local DP")
    add_chainer_flags(parser, batchsize=400)
    add_data_flags(parser, dataset="mnist")
    flag(parser, "--gpu0", type=int, default=0,
         help="accepted for parity (reference device map, "
              "train_mnist_gpu.py:52-67); the mesh covers all local devices")
    flag(parser, "--gpu1", type=int, default=1, help="accepted for parity")
    args = parser.parse_args()
    bootstrap(args)
    strategy = data_parallel_local()
    print(f"ParallelUpdater-style DP over {strategy.num_replicas} local "
          f"device(s)", flush=True)
    trainer = build_trainer(args, strategy)
    if args.resume:
        trainer.resume(args.resume)
    trainer.run()


if __name__ == "__main__":
    main()
