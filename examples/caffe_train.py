#!/usr/bin/env python
"""``caffe train`` — the Caffe-idiom entry point.

The reference's caffe/ track is an empty placeholder (reference
caffe/README.md is zero-byte; declared at README.md:4-20), so this script
gives the track's canonical surface a TPU-native implementation: a solver
prototxt names a net prototxt and the optimization schedule; the net compiles
to one XLA program; ``--gpu all`` style multi-device becomes the framework's
DataParallel strategy over the mesh.

    python examples/caffe_train.py --solver caffe/lenet_solver.prototxt
    # resume from the latest snapshot:
    python examples/caffe_train.py --solver caffe/lenet_solver.prototxt --snapshot latest
    # all local devices, data-parallel (caffe's -gpu all):
    python examples/caffe_train.py --solver caffe/lenet_solver.prototxt --gpu all
"""

from common import bootstrap, mnist_arrays, per_process_loader
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train.solver import Solver
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import add_data_flags, add_topology_flags, flag, make_parser


def main():
    parser = make_parser("dtdl_tpu: caffe-style solver training")
    flag(parser, "--solver", required=True,
         help="solver prototxt (SolverParameter text format)")
    flag(parser, "--snapshot", default="",
         help="resume: 'latest' or a snapshot iteration number")
    flag(parser, "--gpu", default="",
         help="'' = single device; 'all' or a count = data parallel "
              "(caffe's -gpu flag; devices are mesh chips here)")
    flag(parser, "--out", default="",
         help="override snapshot/output directory")
    flag(parser, "--max-iter", type=int, default=0,
         help="override the solver's max_iter (0 = use prototxt value)")
    flag(parser, "-b", "--batch-size", "--batchsize", type=int, default=64,
         help="GLOBAL batch size (a data-layer concern in caffe)")
    add_data_flags(parser, dataset="mnist")
    add_topology_flags(parser)
    args = parser.parse_args()
    bootstrap(args)

    seed = seed_everything(0)
    del seed  # Solver seeds from the prototxt's random_seed
    if not args.gpu:
        strategy = choose_strategy("single")
    elif args.gpu == "all":
        strategy = choose_strategy("ddp")
    else:
        # caffe's -gpu 0,1 / count form: data parallel over the first N chips
        import jax
        from dtdl_tpu.runtime.mesh import build_mesh
        n = (len(args.gpu.split(",")) if "," in args.gpu else int(args.gpu))
        strategy = choose_strategy("ddp",
                                   mesh=build_mesh(devices=jax.devices()[:n]))

    (x, y), (vx, vy) = mnist_arrays(args)
    train_loader = per_process_loader(x, y, args.batch_size, shuffle=True,
                                      seed=0)
    test_loader = per_process_loader(vx, vy, args.batch_size, shuffle=False,
                                     seed=0, drop_last=False)

    solver = Solver(args.solver, train_loader, test_loader,
                    strategy=strategy, out=args.out or None,
                    overrides={"max_iter": args.max_iter} if args.max_iter
                    else None)
    if args.snapshot:
        ok = solver.restore(None if args.snapshot == "latest"
                            else int(args.snapshot))
        print(f"resume: {'ok' if ok else 'no snapshot found'} "
              f"(iter {solver.iteration})", flush=True)
    final = solver.solve()
    print("final:", {k: round(v, 4) for k, v in final.items()}, flush=True)
    if solver.test_loader is not None:
        print("test:", {k: round(v, 4) for k, v in solver.test().items()},
              flush=True)


if __name__ == "__main__":
    main()
