#!/usr/bin/env python
"""Elastic multi-process training over the TCP control-plane store.

The multi-process capability the reference's tracks rendezvous with —
PyTorch's ``tcp://`` TCPStore init, the MXNet kvstore ``dist_sync``
idiom — upgraded to the full ISSUE 12/13 elastic machine: every worker
process holds a heartbeat lease in a **real TCP coordinator**
(`dtdl_tpu/parallel/tcpstore.py`), exchanges gradients through it, and
when a peer dies the survivors detect the expired lease, re-form a
generation-fenced world, restore the last committed snapshot, and keep
training at the smaller world — with the coordinator itself
crash-recoverable (WAL + snapshot + a server epoch that refuses
amnesiac restarts by name).

Two ways to run it::

    # one-command demo: in-process coordinator, 4 worker threads,
    # rank 2 crash-injected mid-run — prints the MTTR story
    python examples/elastic_train.py --demo

    # the real shape: one coordinator + one OS process per worker
    # (the launcher hosts the store and threads DTDL_STORE_ADDR)
    python -m dtdl_tpu.launch.local --nproc 4 --serve-store -- \
        examples/elastic_train.py --steps 20 --ckpt-dir /tmp/elastic

In multi-process mode each rank connects via ``tcpstore.connect()``
(reads ``DTDL_STORE_ADDR``), and a killed worker (or a killed-and-
restarted coordinator — see `tests/test_elastic_tcp.py` for both
drills) exercises exactly the recovery documented in SCALING.md
rounds 17/18.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dtdl_tpu.data.sharding import GlobalBatchSampler, elastic_global_batch
from dtdl_tpu.models import MLP
from dtdl_tpu.parallel.kvstore import RetryingStore
from dtdl_tpu.parallel.tcpstore import (TCPStoreClient, TCPStoreServer,
                                        connect, store_addr)
from dtdl_tpu.resil import (ElasticConfig, ElasticWorker, FaultPlan,
                            peer_site, run_workers)
from dtdl_tpu.train import init_state
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import flag, make_parser

N_EXAMPLES, DIM = 512, 32


def make_problem(seed: int):
    """The functional training triple ElasticWorker drives: jitted
    grad/apply plus a host batch builder over a deterministic dataset
    (every rank regenerates the same arrays from the seed — no data
    service needed for the demo)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N_EXAMPLES, DIM)).astype(np.float32)
    y = rng.integers(0, 10, N_EXAMPLES)
    model = MLP(n_units=32)
    state0 = init_state(model, jax.random.PRNGKey(seed),
                        jnp.zeros((1, DIM)), optax.sgd(0.1))

    def loss(p, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply({"params": p}, b["x"]), b["y"]).mean()

    grad_jit = jax.jit(lambda p, b: jax.grad(loss)(p, b))
    apply_jit = jax.jit(lambda s, g, n: s.apply_gradients(
        grads=jax.tree.map(lambda v: v / n, g)))
    problem = dict(
        init_fn=lambda: state0,
        grad_fn=lambda s, b: grad_jit(s.params, b),
        apply_fn=lambda s, g, n: apply_jit(s, g, float(n)),
        batch_fn=lambda i: {"x": jnp.asarray(x[i]),
                            "y": jnp.asarray(y[i])},
    )
    # warm the compiled step before arming any watchdog: a first-call
    # compile inside the deadline reads as a wedged peer (round 17)
    g = jax.device_get(problem["grad_fn"](state0,
                                          problem["batch_fn"](np.arange(4))))
    problem["apply_fn"](state0, g, 2)
    return problem


def mk_worker(store, rank, args, problem):
    cfg = ElasticConfig(heartbeat_s=args.heartbeat_s,
                        watchdog_s=args.watchdog_s,
                        step_timeout_s=args.step_timeout_s,
                        join_grace_s=args.join_grace_s,
                        snapshot_every=args.snapshot_every)
    sampler = GlobalBatchSampler(
        N_EXAMPLES, elastic_global_batch(args.workers,
                                         per_worker=args.batch_size),
        seed=args.seed)
    return ElasticWorker(store, rank, sampler=sampler,
                         total_steps=args.steps, cfg=cfg,
                         ckpt_dir=args.ckpt_dir or None, **problem)


def report(w):
    loss_like = float(np.sum(np.abs(
        np.asarray(jax.tree.leaves(jax.device_get(w.state.params))[0]))))
    print(f"[rank {w.rank}] done={w.done} world=gen{w.world.generation}"
          f"/{list(w.world.ranks)} steps={w.step} "
          f"params_digest={loss_like:.6f}", flush=True)


def run_demo(args):
    """In-process rehearsal of the whole machine: TCP coordinator +
    thread-hosted workers + an injected crash of one rank."""
    server = TCPStoreServer(wal_dir=os.path.join(args.ckpt_dir, "wal")
                            if args.ckpt_dir else None).start()
    print(f"coordinator up at {server.addr} "
          f"(epoch {server.epoch[:8]}...)", flush=True)
    problem = make_problem(args.seed)
    workers = [
        mk_worker(RetryingStore(TCPStoreClient(server.addr), seed=r),
                  r, args, problem)
        for r in range(args.workers)]
    victim = args.workers - 1
    plan = FaultPlan().at(peer_site(victim, "step"),
                          max(1, args.steps // 2), "crash")
    with plan:
        run_workers(workers, timeout_s=300)
    server.stop()
    survivors = [w for w in workers if w.rank != victim]
    dead = workers[victim]
    detect = min(t for w in survivors
                 for n, t, _ in w.events if n == "peer_lost") \
        - dead.stopped_t
    print(f"rank {victim} crashed at step {args.steps // 2}; survivors "
          f"detected in {detect:.3f}s (watchdog {args.watchdog_s}s), "
          f"re-formed, finished:", flush=True)
    for w in survivors:
        report(w)


def run_worker(args):
    """One real worker process: connect to DTDL_STORE_ADDR (threaded
    through by the launcher), join the world, train elastically."""
    addr = args.store_addr or store_addr()
    if not addr:
        raise SystemExit("no store: pass --store-addr, set "
                         "DTDL_STORE_ADDR, or launch via "
                         "`-m dtdl_tpu.launch.local --serve-store`")
    store = connect(addr, retries=10, seed=args.process_id)
    problem = make_problem(args.seed)
    w = mk_worker(store, args.process_id, args, problem)
    w.run()
    report(w)
    if w.error is not None:
        raise SystemExit(f"worker {args.process_id} failed: {w.error!r}")


def main():
    p = make_parser("Elastic training over the TCP control-plane store")
    flag(p, "--demo", action="store_true",
         help="single-command rehearsal: in-process coordinator, "
              "thread workers, one injected crash")
    flag(p, "--workers", type=int, default=4,
         help="world size (demo threads, or the launched nproc)")
    flag(p, "--steps", type=int, default=12)
    flag(p, "--batch-size", type=int, default=8,
         help="per-worker batch at full world (global batch is "
              "elastic_global_batch(workers, per_worker))")
    flag(p, "--ckpt-dir", default="",
         help="commit snapshots here (restores after a shrink)")
    flag(p, "--store-addr", default="",
         help="host:port of a running tcpstore coordinator "
              "(default: $DTDL_STORE_ADDR)")
    flag(p, "--heartbeat-s", type=float, default=0.05)
    flag(p, "--watchdog-s", type=float, default=0.5)
    flag(p, "--step-timeout-s", type=float, default=30.0)
    flag(p, "--join-grace-s", type=float, default=0.5)
    flag(p, "--snapshot-every", type=int, default=2)
    flag(p, "--seed", type=int, default=0)
    flag(p, "--coordinator", default="")      # launcher-appended topology
    flag(p, "--num-processes", type=int, default=1)
    flag(p, "--process-id", type=int, default=0)
    flag(p, "--platform", default="")
    flag(p, "--fake-devices", type=int, default=0)
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    seed_everything(args.seed)
    if args.ckpt_dir:
        os.makedirs(args.ckpt_dir, exist_ok=True)
    if args.demo:
        run_demo(args)
    else:
        if args.num_processes > 1:
            # the launched world IS the world: every rank must size the
            # sampler identically, from the launcher's nproc — a stale
            # --workers default must not win over the real topology
            args.workers = args.num_processes
        run_worker(args)


if __name__ == "__main__":
    main()
