#!/usr/bin/env python
"""Multi-process / multi-host allreduce data parallelism — the flagship path.

Capability parity with reference pytorch/distributed_data_parallel.py (the
repo's centerpiece): multi-process rendezvous, per-process device binding,
gradient allreduce, per-rank dataset sharding, per-rank batch division, SGD +
StepLR(2, 0.1), checkpoint at the end.  TPU-native restatement:

* ``--init-method tcp://host:port`` + ``--rank/--world-size`` →
  ``--coordinator host:port --process-id --num-processes`` into
  `jax.distributed.initialize` (both spellings accepted);
* NCCL bucketed allreduce from ``loss.backward()`` (reference :132) → XLA
  AllReduce over ICI emitted by `lax.pmean` inside the jitted step;
* ``DistributedSampler`` (reference :87-91) → `ShardedSampler` per-host
  stripes of a deterministic global permutation;
* per-*local*-device batch division (reference :71 — subtly wrong across
  nodes) → explicit GLOBAL batch split across all replicas;
* every-rank checkpoint writes (reference :103-115) → leader-only write.

Launch (2 hosts):
    python -m dtdl_tpu.launch.tpu_vm --workers h1,h2 -- \
        examples/distributed_data_parallel.py --batch-size 256
or manually per host, mirroring the reference's shell-per-rank procedure:
    python examples/distributed_data_parallel.py \
        --coordinator h1:8476 --num-processes 2 --process-id 0|1
"""

import jax
import jax.numpy as jnp

from common import bootstrap, build_mesh_from_args, cifar_loaders, sgd_steplr
from dtdl_tpu.ckpt import Checkpointer
from dtdl_tpu.metrics import JsonlSink, Reporter, StdoutSink
from dtdl_tpu.models import pyramidnet
from dtdl_tpu.parallel import DataParallel
from dtdl_tpu.runtime import is_leader
from dtdl_tpu.train import evaluate, init_state, make_eval_step, \
    make_train_step, train_epoch
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_ckpt_flags, add_data_flags,
                                   add_topology_flags, add_train_flags,
                                   flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: multi-host allreduce DDP CIFAR-10")
    add_train_flags(parser, batch_size=64, lr=0.1, epochs=20)
    add_data_flags(parser, dataset="cifar10")
    add_ckpt_flags(parser)
    add_topology_flags(parser)
    flag(parser, "--dist-backend", default="ici",
         help="accepted for parity (reference defaults to 'nccl'); "
              "collectives always ride ICI/DCN via XLA here")
    flag(parser, "--dtype", default="bfloat16",
         choices=["float32", "bfloat16"])
    args = parser.parse_args()

    bootstrap(args)  # rendezvous: jax.distributed.initialize
    key = seed_everything(args.seed)
    strategy = DataParallel(build_mesh_from_args(args))
    if is_leader():
        print(f"DDP over {strategy.num_replicas} replicas on "
              f"{jax.process_count()} process(es); global batch "
              f"{args.batch_size} -> "
              f"{strategy.per_replica_batch(args.batch_size)}/replica",
              flush=True)

    train_loader, val_loader = cifar_loaders(args, args.seed)
    tx, schedule = sgd_steplr(args.lr, args.momentum, args.weight_decay,
                              len(train_loader))
    model = pyramidnet(dtype=jnp.dtype(args.dtype))
    state = strategy.replicate(
        init_state(model, key, jnp.zeros((1, 32, 32, 3)), tx))

    step = make_train_step(strategy)
    eval_step = make_eval_step(strategy)
    sinks = [StdoutSink(prefix=f"[p{jax.process_index()}]")]
    if is_leader():
        sinks.append(JsonlSink(f"{args.out}/log.jsonl"))
    # context-managed reporter: the JSONL sink is closed/flushed even if
    # an epoch raises, so the log file never loses its tail to a crash
    with Reporter(sinks) as reporter:
        for epoch in range(args.epochs):
            state, _ = train_epoch(step, state, train_loader, strategy,
                                   reporter=reporter, epoch=epoch,
                                   log_interval=args.log_interval)
            evaluate(eval_step, state, val_loader, strategy,
                     reporter=reporter, epoch=epoch)
    if args.save_model:
        ckpt = Checkpointer(args.out)
        path = ckpt.save_final(state.params)
        if is_leader():
            print(f"leader saved weights to {path}", flush=True)


if __name__ == "__main__":
    main()
