#!/usr/bin/env python
"""MNIST CNN across multiple workers, fit() API.

Capability parity with reference tensorflow2/mnist_multi_worker_strategy.py:
``MultiWorkerMirroredStrategy`` + TF_CONFIG become a global-mesh
`DataParallel` strategy + `jax.distributed.initialize`.  The reference's
cluster flags are kept: ``--worker_hosts h1:p,h2:p --task_index i`` derive
the coordinator (first host) and process id; ``--job_name Ps`` is accepted
but routed to collective DP, mirroring the reference's worker-only guard
(reference :15-16 rejects it; we warn and proceed with DP, per SURVEY §2.2
'keep the flag surface, route to collective DP').

    # worker 0 and 1 on two hosts:
    python examples/mnist_multi_worker_strategy.py \
        --worker_hosts h1:8476,h2:8476 --task_index 0   # and 1 on h2
"""

from common import bootstrap
from dtdl_tpu.parallel import distributed_data_parallel
from dtdl_tpu.runtime import initialize, is_leader
from dtdl_tpu.utils.config import add_data_flags, flag, make_parser

from mnist_single import add_tf2_flags, run


def main():
    parser = make_parser(
        "dtdl_tpu: Keras-style MNIST CNN (multi-worker collective DP)")
    add_tf2_flags(parser)
    add_data_flags(parser, dataset="mnist")
    flag(parser, "--worker_hosts", "-wh", type=str, default="",
         help="Comma-separated list of hostname:port pairs")
    flag(parser, "--job_name", "-j", type=str, default="worker",
         help="Ps or worker (Ps is routed to collective DP)")
    flag(parser, "--task_index", "-i", type=int, default=0)
    # also accept the generic topology spelling used by the launcher
    flag(parser, "--coordinator", type=str, default="")
    flag(parser, "--num-processes", type=int, default=0)
    flag(parser, "--process-id", type=int, default=-1)
    args = parser.parse_args()

    if args.job_name.lower() == "ps":
        print("parameter-server mode has no TPU runtime; continuing with "
              "collective data parallelism (reference rejects PS outright)",
              flush=True)

    if args.worker_hosts:
        hosts = args.worker_hosts.split(",")
        coordinator = hosts[0]
        num_processes = len(hosts)
        process_id = args.task_index
    else:
        coordinator = args.coordinator
        num_processes = args.num_processes or 1
        process_id = max(args.process_id, 0)
    initialize(coordinator=coordinator, num_processes=num_processes,
               process_id=process_id)
    bootstrap(args)
    strategy = distributed_data_parallel()
    if is_leader():
        print(f"MultiWorker DP over {strategy.num_replicas} replicas",
              flush=True)
    run(args, strategy)


if __name__ == "__main__":
    main()
