#!/usr/bin/env python
"""4D-parallel LM training: dp x sp x pp x tp (+ expert parallel).

The scale path past the reference's pure data parallelism: one shard_map'd
step over a ('data','seq','pipe','model') mesh — ring attention over 'seq'
for long context, GPipe microbatching over 'pipe', Megatron tensor parallel
and expert-parallel MoE over 'model' (see dtdl_tpu/parallel/megatron.py).

On one host this runs over the local devices; pass the usual coordinator
flags for multi-host.  The mesh is factored automatically unless
``--mesh data,seq,pipe,model`` sizes are given.

    python examples/train_lm_4d.py --steps 20 --batch-size 8 --seq-len 128
    python examples/train_lm_4d.py --steps 2 \
        --platform cpu --fake-devices 8           # 8-device CPU dry run
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from common import bootstrap
from dtdl_tpu.data import load_dataset
from dtdl_tpu.metrics import Reporter, StdoutSink
from dtdl_tpu.parallel import megatron as M
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_data_flags, add_topology_flags,
                                   add_train_flags, flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: 4D-parallel (dp/sp/pp/tp+ep) LM training")
    add_train_flags(parser, batch_size=8, lr=1e-3, epochs=1)
    add_data_flags(parser, dataset="synthetic_lm")
    add_topology_flags(parser)
    flag(parser, "--steps", type=int, default=20, help="train steps to run")
    flag(parser, "--seq-len", type=int, default=128)
    flag(parser, "--d-model", type=int, default=128)
    flag(parser, "--n-heads", type=int, default=8)
    flag(parser, "--d-ff", type=int, default=256)
    flag(parser, "--layers-per-stage", type=int, default=1)
    flag(parser, "--n-experts", type=int, default=0,
         help="0 = dense MLP; >0 enables expert-parallel MoE")
    flag(parser, "--moe-dispatch", default="routed",
         choices=["routed", "dense"],
         help="MoE dispatch: capacity-factor top-1 + all-to-all (routed) "
              "or the dense one-hot oracle")
    flag(parser, "--capacity-factor", type=float, default=1.25,
         help="per-expert token slots = cf * tokens * k / n_experts (routed)")
    flag(parser, "--moe-top-k", type=int, default=1,
         help="experts per token: 1 = Switch routing, 2 = GShard-style "
              "renormalized top-2")
    flag(parser, "--moe-aux-weight", type=float, default=0.01,
         help="Switch load-balance aux loss weight (added to the training "
              "loss; 0 disables)")
    flag(parser, "--microbatches", type=int, default=2)
    flag(parser, "--schedule", default="1f1b", choices=["1f1b", "gpipe"],
         help="pipeline schedule")
    flag(parser, "--virtual-stages", type=int, default=1,
         help=">1 = interleaved 1F1B: v layer chunks per device shrink "
              "the pipeline bubble (requires --schedule 1f1b and "
              "layers-per-stage divisible by v)")
    flag(parser, "--mesh", default="",
         help="data,seq,pipe,model sizes, e.g. 1,2,2,2 (default: auto)")
    flag(parser, "--out", "-o", default="",
         help="checkpoint directory (empty = no checkpointing)")
    flag(parser, "--resume", "-r", action="store_true",
         help="resume from the latest snapshot in --out")
    flag(parser, "--ckpt-interval", type=int, default=0,
         help="snapshot every N steps (0 = only at the end)")
    flag(parser, "--eval-interval", type=int, default=0,
         help="run held-out validation every N steps (0 = only at the "
              "end); reference parity: every reference script evaluates")
    flag(parser, "--eval-batches", type=int, default=2,
         help="validation batches per evaluation")
    flag(parser, "--generate-tokens", type=int, default=0,
         help=">0: after training, convert the 4D params to the flax "
              "tree (megatron.to_flax_params) and greedily decode this "
              "many tokens — the train-4D/serve-with-generate bridge "
              "(single-process runs only)")
    args = parser.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.dataset != "synthetic_lm":
        raise SystemExit("train_lm_4d.py trains on token data; "
                         "use --dataset synthetic_lm")

    bootstrap(args)
    seed_everything(args.seed)

    if args.mesh:
        shape = tuple(int(x) for x in args.mesh.split(","))
        if len(shape) != 4:
            raise SystemExit("--mesh needs 4 sizes: data,seq,pipe,model")
        from dtdl_tpu.runtime import build_mesh
        mesh = build_mesh(shape, M.AXES)
    else:
        mesh = M.build_4d_mesh()
    shape = dict(mesh.shape)

    vocab = 256
    cfg = M.MegatronConfig(
        vocab_size=vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_ff=args.d_ff, n_stages=shape["pipe"],
        layers_per_stage=args.layers_per_stage,
        n_experts=args.n_experts, max_seq=args.seq_len,
        n_microbatches=args.microbatches, schedule=args.schedule,
        virtual_stages=args.virtual_stages,
        moe_dispatch=args.moe_dispatch,
        capacity_factor=args.capacity_factor,
        moe_top_k=args.moe_top_k,
        moe_aux_weight=args.moe_aux_weight)
    if args.n_experts and args.n_experts % shape["model"]:
        raise SystemExit(f"--n-experts must be divisible by tp={shape['model']}")

    # seq_len+1 tokens per sequence so that the shifted inputs/targets both
    # span seq_len positions (the 'seq' mesh axis must divide them evenly)
    train_tokens, test_tokens = load_dataset(
        args.dataset, seq_len=args.seq_len + 1, vocab_size=vocab)
    if args.batch_size % shape["data"] or \
            (args.batch_size // shape["data"]) % args.microbatches:
        raise SystemExit("--batch-size must be divisible by data-axis size "
                         "times --microbatches")
    if shape["seq"] > 1 and args.seq_len % (2 * shape["seq"]):
        raise SystemExit("--seq-len must be divisible by 2x the seq-axis "
                         "size (zigzag ring layout)")
    params = M.place_params(mesh, cfg,
                            M.init_params(cfg, jax.random.PRNGKey(args.seed)))
    opt = optax.adamw(args.lr)
    opt_state = M.init_optimizer(cfg, mesh, opt, params)
    step = M.make_megatron_train_step(cfg, mesh, opt)

    # checkpoint/resume for the 4D path: snapshots hold the SHARDED
    # (params, opt_state) — orbax writes/reads per-host shards against the
    # abstract_state target, no gather — plus the step counter, so an
    # interrupted run (or the launcher's --max-restarts) continues exactly
    ckpt = start_step = None
    if args.out:
        from dtdl_tpu.ckpt import Checkpointer
        ckpt = Checkpointer(args.out, keep=3)
        if args.resume:
            a_params, a_opt = M.abstract_state(cfg, mesh, opt)
            like = {"params": a_params, "opt_state": a_opt,
                    "step": jax.ShapeDtypeStruct((), np.int64)}
            snap, at = ckpt.restore(like)
            if snap is not None:
                params, opt_state = snap["params"], snap["opt_state"]
                start_step = int(snap["step"])
                print(f"resumed from snapshot at step {start_step}",
                      flush=True)
    start_step = start_step or 0
    if start_step >= args.steps:
        # e.g. the launcher's --max-restarts rerunning a job whose
        # end-of-run snapshot already exists: nothing to train, exit clean
        print(f"already complete: snapshot at step {start_step} >= "
              f"--steps {args.steps}; nothing to do", flush=True)
        ckpt.close()
        return

    reporter = Reporter([StdoutSink()])
    B, S = args.batch_size, args.seq_len
    n_seqs = len(train_tokens)
    loss = float("nan")

    # held-out validation on the 4D mesh: forward-only eval step, metrics
    # allreduced exactly (reference parity: tensorflow2/mnist_single.py
    # evaluates after restore; chainer/train_mnist_multi.py allreduces its
    # evaluator) — token-weighted mean over --eval-batches batches
    eval_step = M.make_megatron_eval_step(cfg, mesh)

    def run_eval(step_no):
        loss_sum = correct_sum = tok_sum = 0.0
        for j in range(args.eval_batches):
            take = np.arange(j * B, (j + 1) * B) % len(test_tokens)
            toks = test_tokens[take]
            vb = M.shard_lm_batch(mesh, {
                "tokens": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32),
                "mask": np.ones((B, S), np.float32),
            })
            m = eval_step(params, vb["tokens"], vb["targets"], vb["mask"])
            n = float(m["n_tokens"])
            loss_sum += float(m["loss"]) * n
            correct_sum += float(m["accuracy"]) * n
            tok_sum += n
        reporter.report({"step": step_no,
                         "val_loss": loss_sum / max(tok_sum, 1.0),
                         "val_accuracy": correct_sum / max(tok_sum, 1.0),
                         "val_tokens": tok_sum})
    try:
        for i in range(start_step, args.steps):
            take = np.arange(i * B, (i + 1) * B) % n_seqs
            toks = train_tokens[take]
            batch = M.shard_lm_batch(mesh, {
                "tokens": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32),
                "mask": np.ones((B, S), np.float32),
            })
            params, opt_state, loss, metrics = step(
                params, opt_state, batch["tokens"], batch["targets"],
                batch["mask"])
            done = i + 1
            if i % args.log_interval == 0:
                reporter.report({"step": i, "loss": float(loss),
                                 "mesh": str(shape),
                                 **{k: float(v) for k, v in metrics.items()}})
            if args.eval_interval and done % args.eval_interval == 0:
                run_eval(done)
            if ckpt and ((args.ckpt_interval and done % args.ckpt_interval
                          == 0) or done == args.steps):
                ckpt.save(done, {"params": params, "opt_state": opt_state,
                                 "step": np.asarray(done, np.int64)})
    finally:
        if ckpt:
            ckpt.wait_until_finished()
            ckpt.close()
    if not args.eval_interval or args.steps % args.eval_interval:
        run_eval(args.steps)   # end-of-run validation (always)

    if args.generate_tokens:
        if jax.process_count() > 1:
            print("skipping --generate-tokens: multi-process params are "
                  "not fully addressable on one host", flush=True)
        else:
            # the serving bridge: 4D stacked params -> flax tree ->
            # KV-cache decode.  The MoE keeps the TRAINED routing
            # semantics (routed capacity, same cf/top_k — single-token
            # steps get one-slot groups, so decode never drops); the
            # rope table is extended to fit the requested decode length
            # (rows depend only on position — numerically identical)
            from dtdl_tpu.models import generate
            flax_p = M.to_flax_params(cfg, jax.device_get(params))
            lm = M.to_flax_model(
                cfg, max_seq=max(args.seq_len, 8 + args.generate_tokens))
            prompt = jnp.asarray(train_tokens[:1, :8], jnp.int32)
            toks_out = generate(lm, flax_p, prompt,
                                max_new_tokens=args.generate_tokens)
            print("generated:", np.asarray(toks_out)[0].tolist(),
                  flush=True)

    print(f"final loss {float(loss):.6f} at step {args.steps} "
          f"on mesh {shape}", flush=True)


if __name__ == "__main__":
    main()
