#!/usr/bin/env python
"""ResNet-50 DDP training — the north-star throughput workload.

BASELINE.json names "ResNet-50/ImageNet PyTorch DDP on v4-32 (SLURM ->
TPU-VM launcher)" among the configs to cover.  This script is that workload
TPU-native: ResNet-50 v1.5 in bfloat16 (float32 BN stats), data-parallel
over every chip in the mesh via shard_map + psum gradient sync, per-host
data sharding, SGD + cosine schedule with linear warmup, throughput
(samples/sec and samples/sec/chip) reported every log interval.

ImageNet itself isn't distributable with the repo; with no dataset present a
deterministic learnable synthetic set stands in at full 224x224x3 resolution
so the compute/communication profile is the real one.

    python examples/imagenet_resnet50.py --batch-size 256 --steps 100
    # multi-host (or zero-flag under SLURM; see launch/slurm.py):
    python examples/imagenet_resnet50.py --coordinator h0:8476 \
        --num-processes 4 --process-id $RANK --batch-size 1024
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from common import bootstrap, per_process_loader
from dtdl_tpu.data.synthetic import class_pattern_images
from dtdl_tpu.models import resnet50
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_eval_step, make_train_step
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_data_flags, add_topology_flags, flag,
                                   make_parser)


def main():
    parser = make_parser("dtdl_tpu: ResNet-50 DDP throughput workload")
    flag(parser, "-b", "--batch-size", type=int, default=256,
         help="GLOBAL batch size")
    flag(parser, "--steps", type=int, default=100)
    flag(parser, "--lr", type=float, default=0.1,
         help="base lr at batch 256 (scaled linearly with batch size)")
    flag(parser, "--warmup-steps", type=int, default=20)
    flag(parser, "--image-size", type=int, default=224)
    flag(parser, "--num-classes", type=int, default=1000)
    flag(parser, "--train-examples", type=int, default=4096,
         help="synthetic training pool size")
    flag(parser, "--log-interval", type=int, default=20)
    flag(parser, "--dtype", default="bfloat16",
         choices=["bfloat16", "float32"])
    flag(parser, "--s2d-stem", action="store_true",
         help="space-to-depth stem (faster on TPU; renames the stem param "
              "path, so snapshots are not interchangeable with the "
              "standard-stem tree)")
    flag(parser, "--seed", type=int, default=0)
    add_data_flags(parser, dataset="synthetic")
    add_topology_flags(parser)
    args = parser.parse_args()
    bootstrap(args)

    key = seed_everything(args.seed)
    strategy = choose_strategy("auto")
    n_chips = max(1, len(jax.devices()))

    model = resnet50(num_classes=args.num_classes,
                     dtype=jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32,
                     s2d_stem=args.s2d_stem)
    base = args.lr * args.batch_size / 256  # linear scaling rule
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, base, args.warmup_steps, max(args.steps, args.warmup_steps + 1))
    tx = optax.chain(optax.add_decayed_weights(1e-4),
                     optax.sgd(schedule, momentum=0.9, nesterov=True))
    state = strategy.replicate(init_state(
        model, key, jnp.zeros((1, args.image_size, args.image_size, 3)), tx))
    train_step = make_train_step(strategy)

    x, y = class_pattern_images(args.train_examples,
                                (args.image_size, args.image_size, 3),
                                args.num_classes, seed=args.seed, noise=0.3)
    loader = per_process_loader(x, y, args.batch_size, shuffle=True,
                                seed=args.seed)

    step_i, t0, logged = 0, time.perf_counter(), 0
    epoch = 0
    while step_i < args.steps:
        loader.set_epoch(epoch)
        for batch in iter(loader):
            if step_i >= args.steps:
                break
            batch = strategy.shard_batch(batch)
            state, metrics = train_step(state, batch)
            step_i += 1
            if step_i % args.log_interval == 0 or step_i == args.steps:
                # a VALUE FETCH, not block_until_ready: on the tunneled TPU
                # backend the latter returns before execution finishes and
                # would overstate throughput ~10x (see bench.py)
                loss = float(metrics["loss"])
                dt = time.perf_counter() - t0
                done = step_i - logged
                sps = args.batch_size * done / dt
                print(f"step {step_i}/{args.steps} "
                      f"loss {loss:.4f} "
                      f"acc {float(metrics['accuracy']):.4f} "
                      f"| {sps:,.0f} samples/sec "
                      f"({sps / n_chips:,.0f}/chip, {n_chips} chips) "
                      f"| {dt / done * 1e3:.1f} ms/step", flush=True)
                t0, logged = time.perf_counter(), step_i
        epoch += 1

    # quick sanity eval on the training pool (synthetic data is learnable)
    eval_step = make_eval_step(strategy)
    em = eval_step(state, strategy.shard_batch(
        {"image": jnp.asarray(x[: args.batch_size]),
         "label": jnp.asarray(y[: args.batch_size])}))
    print(f"final: train-pool acc "
          f"{float(em['correct_sum']) / float(em['count']):.4f}", flush=True)


if __name__ == "__main__":
    main()
