#!/usr/bin/env python
"""Serving fleet demo: a health-checked Router over N engine replicas.

Synthetic traffic against a replica fleet (dtdl_tpu/serve/fleet.py):
least-loaded dispatch, circuit-breaker failure detection, deterministic
failover with retries, opt-in straggler hedging, rolling restarts —
everything the single-engine serve_lm.py demo cannot survive, it can.

    python examples/serve_fleet.py                       # 2 replicas
    python examples/serve_fleet.py --n-replicas 3 --n-requests 64
    # live failover: kill replica 0's worker after its 5th iteration —
    # watch the eviction, the retries, and ZERO lost requests
    python examples/serve_fleet.py --kill-replica-after 5
    # rolling restart under traffic
    python examples/serve_fleet.py --rolling-restart
    # tail-latency hedging
    python examples/serve_fleet.py --hedge-after 0.05
    # full observability pipeline: correlated tracing + continuous
    # export + SLO judging — writes a Perfetto trace, a JSONL series,
    # serves GET /metrics, and prints one request's correlated timeline
    python examples/serve_fleet.py --trace /tmp/fleet.json \
        --metrics-jsonl /tmp/fleet_series.jsonl --metrics-port 0 \
        --slo-ttft-p99 0.5 --slo-availability 0.999
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

from common import bootstrap
from dtdl_tpu.models import transformer_lm
from dtdl_tpu.serve import InferenceEngine, Request, Router
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import flag, make_parser


def main():
    parser = make_parser("dtdl_tpu: replicated LM serving fleet")
    flag(parser, "--model-size", default="tiny",
         choices=["tiny", "small", "base"])
    flag(parser, "--n-replicas", type=int, default=2)
    flag(parser, "--n-slots", type=int, default=4,
         help="decode batch width per replica")
    flag(parser, "--n-requests", type=int, default=32)
    flag(parser, "--max-new-tokens", type=int, default=24)
    flag(parser, "--retry-budget", type=int, default=3,
         help="re-dispatches per request after a replica failure")
    flag(parser, "--hedge-after", type=float, default=0.0,
         help="re-submit a straggler to a second replica after this "
              "many seconds (0 = hedging off); first completion wins")
    flag(parser, "--kill-replica-after", type=int, default=-1,
         help="fault injection: kill replica 0's worker thread at its "
              "K-th iteration (-1 = off) — the live failover demo")
    flag(parser, "--rolling-restart", action="store_true",
         help="drain+restart every replica mid-traffic")
    flag(parser, "--watchdog", type=float, default=0.25,
         help="seconds of stale worker heartbeat (with work "
              "outstanding) before the stall signal fires")
    flag(parser, "--trace", default=None,
         help="write a request-correlated Chrome trace here "
              "(Perfetto-loadable; spans + per-request flow events)")
    flag(parser, "--metrics-jsonl", default=None,
         help="append continuous window-delta metric snapshots here "
              "(one JSON object per sampled boundary)")
    flag(parser, "--metrics-port", type=int, default=-1,
         help="serve GET /metrics (Prometheus text) on this port "
              "(0 = pick a free port; -1 = off)")
    flag(parser, "--metrics-interval", type=float, default=0.25,
         help="minimum seconds between exported snapshots")
    flag(parser, "--slo-ttft-p99", type=float, default=0.0,
         help="SLO: router-clock TTFT p99 target in seconds "
              "(0 = off); crossings land in the trace AND the series")
    flag(parser, "--slo-availability", type=float, default=0.0,
         help="SLO: availability floor, e.g. 0.999 (0 = off); bad = "
              "failed + expired over a rolling window")
    flag(parser, "--disagg", action="store_true",
         help="prefill/decode disaggregation: replica 0 serves only "
              "prompt prefills (chunked), the rest only decode — "
              "completed prefills migrate via page-granular KV handoff "
              "(forces a paged engine)")
    flag(parser, "--chunk-tokens", type=int, default=0,
         help="chunked prefill on every replica: per-step prompt token "
              "budget (0 = whole-prompt; implied 16 under --disagg)")
    flag(parser, "--lora", default="",
         help="multi-tenant LoRA across the fleet: comma-separated "
              "adapter checkpoint paths; requests round-robin over "
              "base + adapters (a missing path gets a random demo "
              "adapter saved there)")
    flag(parser, "--lora-rank", type=int, default=8,
         help="adapter rank for --lora (must match saved adapters)")
    flag(parser, "--json-schema", default="",
         help="grammar-constrained decoding: a JSON-schema file; every "
              "request's output is masked to valid JSON for it")
    flag(parser, "--stream", action="store_true",
         help="attach a TokenStream per request — delivery stays "
              "prefix-stable across retries and hedges (only the "
              "winning attempt streams)")
    flag(parser, "--seed", type=int, default=0)
    args = parser.parse_args()
    bootstrap(args)
    seed_everything(args.seed)

    model = transformer_lm(args.model_size, attn_impl="dense",
                           dtype=jnp.float32)
    import flax.linen as nn
    params = nn.unbox(model.init(jax.random.PRNGKey(args.seed),
                                 jnp.zeros((1, 8), jnp.int32))["params"])
    roles = None
    if args.disagg:
        if args.n_replicas < 2:
            parser.error("--disagg needs >= 2 replicas")
        roles = ["prefill"] + ["decode"] * (args.n_replicas - 1)
        if not args.chunk_tokens:
            args.chunk_tokens = 16
    lora_paths = [p for p in args.lora.split(",") if p]
    for p in lora_paths:
        import os
        if not os.path.exists(p):
            from dtdl_tpu.ckpt import save_weights
            from dtdl_tpu.serve import adapter_template
            tpl = adapter_template(params, rank=args.lora_rank)
            arng = np.random.default_rng(hash(p) % (2 ** 31))
            save_weights(p, jax.tree_util.tree_map(
                lambda x: np.asarray(arng.normal(0, 0.02, x.shape),
                                     np.float32), tpl))
            print(f"  --lora: saved demo adapter to {p}")
    engine = InferenceEngine(model, params, n_slots=args.n_slots,
                             buckets=(64,),
                             page_size=16 if args.disagg else 0,
                             lora_rank=(args.lora_rank if lora_paths
                                        else 0),
                             lora_adapters=(len(lora_paths) + 1
                                            if lora_paths else 0))

    plan = None
    if args.kill_replica_after >= 0:
        from dtdl_tpu.resil import FaultPlan
        from dtdl_tpu.resil.faults import replica_site
        plan = FaultPlan().at(replica_site(0, "loop"),
                              args.kill_replica_after)
        print(f"fault armed: replica 0's worker dies at loop "
              f"iteration {args.kill_replica_after}")

    dfa = None
    eos = None
    if args.json_schema:
        import json as _json
        if model.vocab_size < 128:
            parser.error("--json-schema needs a vocab covering ASCII "
                         f"(>= 128); this model has {model.vocab_size}")
        from dtdl_tpu.serve import byte_vocab, compile_json_schema
        with open(args.json_schema) as f:
            schema = _json.load(f)
        eos = model.vocab_size - 1
        dfa = compile_json_schema(schema, byte_vocab(model.vocab_size),
                                  eos_id=eos)

    from dtdl_tpu.serve import TokenStream
    rng = np.random.default_rng(args.seed)
    hi = min(64, model.max_seq // 2)
    tenants = [None] + lora_paths
    streams = [TokenStream() if args.stream else None
               for _ in range(args.n_requests)]
    reqs = [Request(rng.integers(0, model.vocab_size,
                                 int(rng.integers(4, hi))).tolist(),
                    args.max_new_tokens,
                    adapter=tenants[i % len(tenants)],
                    grammar=dfa,
                    eos_id=(eos if dfa is not None else None),
                    stream=streams[i])
            for i in range(args.n_requests)]

    # the round-16 observability pipeline (all opt-in): correlated
    # tracing, continuous boundary-sampled export, SLO judging
    from dtdl_tpu.obs import JsonlSeriesSink, MetricsExporter, Observer
    from dtdl_tpu.serve import default_fleet_slos
    observer = Observer(trace=bool(args.trace), trace_path=args.trace)
    exporter = None
    if (args.metrics_jsonl or args.metrics_port >= 0
            or args.slo_ttft_p99 or args.slo_availability):
        sinks = ([JsonlSeriesSink(args.metrics_jsonl)]
                 if args.metrics_jsonl else [])
        exporter = MetricsExporter(sinks=sinks,
                                   interval_s=args.metrics_interval)
        if args.metrics_port >= 0:
            port = exporter.serve_http(port=args.metrics_port)
            print(f"scraping: curl http://127.0.0.1:{port}/metrics")
    slos = default_fleet_slos(
        ttft_p99_s=args.slo_ttft_p99 or None,
        availability=args.slo_availability or None) or None

    t0 = time.perf_counter()
    with Router(engine, n_replicas=args.n_replicas, plan=plan,
                retry_budget=args.retry_budget,
                hedge_after_s=args.hedge_after or None,
                watchdog_s=args.watchdog, observer=observer,
                exporter=exporter, slos=slos, roles=roles,
                sched_kwargs={
                    "harvest_lag": 4,
                    "chunk_tokens": args.chunk_tokens or None,
                }) as router:
        for r in reqs:
            router.submit(r)
        if args.rolling_restart:
            router.rolling_restart(timeout_s=120)
            print(f"rolling restart done at "
                  f"{time.perf_counter() - t0:.2f}s — traffic continued")
        if not router.wait(reqs, timeout_s=600):
            print("WARNING: fleet did not settle "
                  f"(pump_error={router.pump_error})")
        dt = time.perf_counter() - t0
        evicts = list(router.evict_log)
    # summary AFTER shutdown: the books are settled and the exporter's
    # final forced snapshot (and any SLO verdicts on it) are included
    s = router.summary()

    n_ok = sum(1 for r in reqs if r.done and r.error is None)
    n_err = sum(1 for r in reqs if r.error is not None)
    print(f"served {s['fleet_requests_finished']}/{len(reqs)} requests "
          f"over {args.n_replicas} replicas in {dt:.2f}s  "
          f"({s['fleet_decode_tokens_per_sec']} tok/s fleet-wide; "
          f"{n_ok} clean, {n_err} with named errors)")
    if "fleet_ttft_s_p50" in s:
        print(f"  ttft p50/p95/p99 (router clock, queue+failover "
              f"included): {s['fleet_ttft_s_p50'] * 1e3:.1f} / "
              f"{s['fleet_ttft_s_p95'] * 1e3:.1f} / "
              f"{s['fleet_ttft_s_p99'] * 1e3:.1f} ms")
    print(f"  resilience: retries {s['fleet_retries']}  evictions "
          f"{s['fleet_evictions']}  failovers {s['fleet_failovers']}  "
          f"restarts {s['fleet_restarts']}  hedges "
          f"{s['fleet_hedges']} (won {s['fleet_hedges_won']})")
    if roles is not None:
        print(f"  disaggregation ({'/'.join(roles)}): migrations "
              f"{s['fleet_migrations']}  kv pages moved "
              f"{s['fleet_kv_handoff_pages']}")
    if lora_paths:
        by = s["fleet_tokens_by_adapter"]
        mix = "  ".join(f"{k.rsplit('/', 1)[-1]}={v}"
                        for k, v in sorted(by.items()))
        print(f"  multi-lora ({len(lora_paths)} adapters, rank "
              f"{args.lora_rank}): tokens by tenant: {mix}")
    if dfa is not None:
        n_json = sum(1 for r in reqs if r.error is None)
        print(f"  constrained ({args.json_schema}): {n_json}/{len(reqs)} "
              f"valid; illegal draft tokens trimmed "
              f"{s['fleet_grammar_rejected_tokens']}")
    if args.stream:
        n_div = sum(1 for st in streams if st is not None and st.divergent)
        n_match = sum(1 for r, st in zip(reqs, streams)
                      if st is not None and r.error is None
                      and st.tokens == r.tokens)
        print(f"  streaming: {s['fleet_stream_deliveries']} deliveries; "
              f"{n_match}/{n_ok} clean streams token-exact, "
              f"{n_div} divergent (must be 0 — losers never stream)")
    for ev in evicts:
        lat = (f"{ev['detect_latency_s'] * 1e3:.1f}ms after worker "
               f"death" if ev["detect_latency_s"] is not None
               else "passive signals")
        print(f"  evicted replica {ev['replica']} ({lat}); "
              f"{ev['failovers']} in-flight requests failed over: "
              f"{ev['reason'][:80]}")
    acc = (s["fleet_requests_finished"] + s["fleet_requests_rejected"]
           + s["fleet_requests_expired"] + s["fleet_requests_failed"]
           + s["fleet_requests_aborted"])
    print(f"  accounting: submitted {s['fleet_requests_submitted']} == "
          f"finished {s['fleet_requests_finished']} + rejected "
          f"{s['fleet_requests_rejected']} + expired "
          f"{s['fleet_requests_expired']} + failed "
          f"{s['fleet_requests_failed']} + aborted "
          f"{s['fleet_requests_aborted']}  "
          f"[{'OK' if s['fleet_accounting_ok'] and acc else 'VIOLATED'}]"
          f"  requests lost: {s['fleet_requests_submitted'] - acc}")
    print(f"  replica health: {s['replica_health']}")
    if exporter is not None:
        slo_bits = {k: v for k, v in s.items() if k.startswith("slo_")}
        print(f"  export: {s.get('export_snapshots', 0)} snapshots"
              + (f" -> {args.metrics_jsonl}" if args.metrics_jsonl
                 else "")
              + (f"  SLO: {slo_bits}" if slo_bits else ""))
        exporter.close()
    if args.trace:
        # one request's correlated story, reconstructed from the trace:
        # intake -> dispatch (every attempt, with lineage) -> admit ->
        # first token -> terminal — what Perfetto draws as flow arrows
        probe = next((r for r in reqs if r.done), None)
        if probe is not None:
            tl = observer.request_timeline(probe.rid)
            steps = [f"{e['ts'] / 1e6:+.3f}s {e['name']}"
                     + (f"[{e['args']['lineage']}]"
                        if e.get("args", {}).get("lineage") else "")
                     for e in tl if e.get("ph") in ("i", "X")]
            print(f"  timeline rid={probe.rid}: " + " -> ".join(steps))
        observer.close()
        print(f"  trace written to {args.trace} (load in Perfetto; "
              f"flow arrows join each request's attempts)")


if __name__ == "__main__":
    main()
