#!/usr/bin/env python
"""GSPMD-sharded LM training: tp / fsdp / ep by logical rules.

The compiler-partitioned complement to the strategy layer (train_lm.py)
and the manual-SPMD 4D engine (train_lm_4d.py): every TransformerLM
parameter carries flax logical axis names, and a rule preset
(parallel/tensor.py RULE_PRESETS) maps them to mesh axes — XLA's SPMD
partitioner inserts the collectives.  `--rules tp` is Megatron tensor
parallelism, `--rules fsdp` is ZeRO-3, `--rules tp_fsdp` both, and
`--rules ep` shards the MoE expert dim so a routed-dispatch mixture
trains with real expert parallelism (the token all-to-all is inserted
by GSPMD around the grouped dispatch einsums).

The reference has no model parallelism at all (SURVEY §2.2: TP/PP/EP
marked absent) — this is part of the framework's beyond-parity scale
path, exposed as a runnable script like every other capability.

    python examples/train_lm_gspmd.py --rules tp --platform cpu \
        --fake-devices 8 --mesh 2,4
    python examples/train_lm_gspmd.py --rules ep --n-experts 4 \
        --moe-dispatch routed --platform cpu --fake-devices 8 --mesh 2,4
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from common import bootstrap
from dtdl_tpu.data import load_dataset
from dtdl_tpu.metrics import Reporter, StdoutSink
from dtdl_tpu.models import transformer_lm
from dtdl_tpu.parallel.tensor import (RULE_PRESETS, init_sharded_lm,
                                      make_sharded_lm_eval_step,
                                      make_sharded_lm_train_step)
from dtdl_tpu.runtime.mesh import build_mesh
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_data_flags, add_topology_flags,
                                   add_train_flags, flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: GSPMD-sharded LM training "
                         "(tp / fsdp / ep logical rules)")
    add_train_flags(parser, batch_size=8, lr=1e-3, epochs=1)
    add_data_flags(parser, dataset="synthetic_lm")
    add_topology_flags(parser)
    flag(parser, "--rules", default="tp", choices=sorted(RULE_PRESETS),
         help="logical-axis rule preset (parallel/tensor.py)")
    flag(parser, "--steps", type=int, default=20)
    flag(parser, "--seq-len", type=int, default=128)
    flag(parser, "--model-size", default="tiny",
         choices=["tiny", "small", "base"])
    flag(parser, "--n-experts", type=int, default=0,
         help=">0: MoE MLPs (use --rules ep for expert parallelism)")
    flag(parser, "--moe-dispatch", default="routed",
         choices=["routed", "dense"])
    flag(parser, "--capacity-factor", type=float, default=1.25)
    flag(parser, "--moe-top-k", type=int, default=1)
    flag(parser, "--mesh", default="",
         help="data,model sizes, e.g. 2,4 (default: all devices on "
              "'data' for fsdp/replicated, split 2-ways onto 'model' "
              "otherwise)")
    flag(parser, "--eval-batches", type=int, default=2,
         help="held-out validation batches after training")
    args = parser.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.dataset != "synthetic_lm":
        raise SystemExit("train_lm_gspmd.py trains on token data; "
                         "use --dataset synthetic_lm")

    bootstrap(args)
    key = seed_everything(args.seed)

    n = jax.device_count()
    if args.mesh:
        shape = tuple(int(x) for x in args.mesh.split(","))
        if len(shape) != 2:
            raise SystemExit("--mesh needs 2 sizes: data,model")
    elif args.rules in ("fsdp", "replicated"):
        shape = (n, 1)
    else:
        shape = (n // 2, 2) if n % 2 == 0 and n > 1 else (n, 1)
    mesh = build_mesh(shape, ("data", "model"))
    if args.batch_size % shape[0]:
        raise SystemExit(f"--batch-size must be divisible by the data "
                         f"axis size {shape[0]}")

    vocab = 256
    # dense attention: its einsums partition cleanly under GSPMD (the
    # Pallas flash kernel pairs with the shard_map strategies instead)
    model = transformer_lm(
        args.model_size, max_seq=args.seq_len, attn_impl="dense",
        vocab_size=vocab, n_experts=args.n_experts, moe_every=1,
        moe_dispatch=args.moe_dispatch,
        capacity_factor=args.capacity_factor, moe_top_k=args.moe_top_k)

    train_tokens, test_tokens = load_dataset(
        args.dataset, seq_len=args.seq_len + 1, vocab_size=vocab)
    tx = optax.adamw(args.lr)
    # init with the step's INPUT length: the train step shifts the
    # (seq_len+1)-token batch into seq_len inputs/targets
    toks0 = jnp.zeros((1, args.seq_len), jnp.int32)
    params, opt_state, sh = init_sharded_lm(model, mesh, tx, toks0,
                                            rules=args.rules, rng=key)
    step = make_sharded_lm_train_step(model, mesh, tx, sh,
                                      rules=args.rules)

    reporter = Reporter([StdoutSink()])
    B = args.batch_size
    batch_sh = NamedSharding(mesh, P("data"))
    loss = float("nan")
    for i in range(args.steps):
        take = np.arange(i * B, (i + 1) * B) % len(train_tokens)
        # stage the host array straight into its shards (one transfer)
        batch = jax.device_put(
            np.ascontiguousarray(train_tokens[take], np.int32), batch_sh)
        params, opt_state, loss = step(params, opt_state, batch)
        if i % args.log_interval == 0:
            reporter.report({"step": i, "loss": float(loss),
                             "rules": args.rules, "mesh": str(shape)})

    # held-out validation under the same shardings (reference parity:
    # every reference script evaluates — SURVEY §5.4/§5.5), token-
    # weighted over --eval-batches batches like train_lm_4d.py's
    ev = make_sharded_lm_eval_step(model, mesh, sh, rules=args.rules)
    loss_sum = acc_sum = tok_sum = 0.0
    for j in range(args.eval_batches):
        take = np.arange(j * B, (j + 1) * B) % len(test_tokens)
        vb = jax.device_put(
            np.ascontiguousarray(test_tokens[take], np.int32), batch_sh)
        m = ev(params, vb)
        n = float(m["n_tokens"])
        loss_sum += float(m["loss"]) * n
        acc_sum += float(m["accuracy"]) * n
        tok_sum += n
    reporter.report({"step": args.steps,
                     "val_loss": loss_sum / max(tok_sum, 1.0),
                     "val_accuracy": acc_sum / max(tok_sum, 1.0),
                     "val_tokens": tok_sum})
    print(f"final loss {float(loss):.6f} rules={args.rules} "
          f"mesh={shape}", flush=True)


if __name__ == "__main__":
    main()
