#!/usr/bin/env python
"""MNIST MLP with the Trainer+extensions API (CPU or one device).

Capability parity with reference chainer/train_mnist.py: MLP-1000, Adam,
Trainer with Evaluator / dump_graph / snapshot / LogReport / PrintReport
extensions, ``--resume`` from a snapshot (reference :62-125).  Flag names
match the reference's argparse (:30-47); ``--gpu`` is accepted — device
choice belongs to JAX here.

    python examples/train_mnist.py -b 100 -e 3 -u 1000 -o result
    python examples/train_mnist.py --resume result/snapshot_600 -e 5
"""

import jax
import jax.numpy as jnp
import optax

from common import bootstrap, mnist_arrays, per_process_loader
from dtdl_tpu.models import MLP
from dtdl_tpu.parallel import SingleDevice, choose_strategy
from dtdl_tpu.train import (Evaluator, LogReport, PrintReport, Trainer,
                            dump_graph, init_state, make_eval_step,
                            make_train_step, snapshot)
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import add_data_flags, flag, make_parser


def add_chainer_flags(parser, batchsize=100):
    """Reference chainer/train_mnist.py:30-47 flag surface."""
    flag(parser, "--batchsize", "-b", type=int, default=batchsize)
    flag(parser, "--epoch", "-e", type=int, default=20)
    flag(parser, "--frequency", "-f", type=int, default=-1,
         help="snapshot frequency in epochs (-1 = once per epoch)")
    flag(parser, "--out", "-o", default="result")
    flag(parser, "--resume", "-r", default="")
    flag(parser, "--unit", "-u", type=int, default=1000)
    flag(parser, "--seed", type=int, default=0)


def build_trainer(args, strategy, banner_extra=()):
    key = seed_everything(args.seed)
    (x, y), (vx, vy) = mnist_arrays(args, flatten=True)
    train_loader = per_process_loader(x, y, args.batchsize, shuffle=True,
                                      seed=args.seed)
    val_loader = per_process_loader(vx, vy, args.batchsize, shuffle=False,
                                    seed=args.seed, drop_last=False)
    state = strategy.replicate(init_state(
        MLP(n_units=args.unit), key, jnp.zeros((1, 784)), optax.adam(1e-3)))
    trainer = Trainer(state, make_train_step(strategy), train_loader,
                      strategy, stop_trigger=(args.epoch, "epoch"),
                      out=args.out)
    log = LogReport()
    trainer.extend(Evaluator(make_eval_step(strategy), val_loader, strategy))
    trainer.extend(dump_graph({"image": x[: args.batchsize],
                               "label": y[: args.batchsize]}))
    freq = args.epoch if args.frequency == -1 else max(1, args.frequency)
    trainer.extend(snapshot(), trigger=(freq, "epoch"))
    trainer.extend(log)
    trainer.extend(PrintReport(
        ["epoch", "iteration", "loss", "accuracy",
         "val_loss", "val_accuracy", "elapsed_time"], log))
    return trainer


def main():
    parser = make_parser("dtdl_tpu: Trainer-style MNIST MLP")
    add_chainer_flags(parser)
    add_data_flags(parser, dataset="mnist")
    flag(parser, "--gpu", "-g", type=int, default=-1,
         help="accepted for parity; JAX owns device selection")
    args = parser.parse_args()
    bootstrap(args)

    # rank-0 banner (reference chainer/train_mnist.py:49-58)
    print("=============================================")
    print(f"# device: {jax.devices()[0].device_kind}")
    print(f"# number of units: {args.unit}")
    print(f"# minibatch-size: {args.batchsize}")
    print(f"# epoch: {args.epoch}")
    print("=============================================", flush=True)

    trainer = build_trainer(args, SingleDevice())
    if args.resume:
        trainer.resume(args.resume)
    trainer.run()


if __name__ == "__main__":
    main()
