#!/usr/bin/env python
"""Single-device CIFAR-10 PyramidNet training — the baseline every
distributed variant mutates from.

Capability parity with reference pytorch/single_gpu.py:43-120: one device,
manual epoch/step loop, per-batch loss/acc/batch-time logging every 20 steps,
optional final state_dict save.  Differences by design: the step is one jitted
XLA program, ``--seed`` actually seeds (the reference parses and drops it,
single_gpu.py:32-33), and the device is whatever JAX exposes (TPU chip here,
CPU elsewhere) instead of cuda:0.

    python examples/single_device.py --batch-size 64 --lr 0.1 --epochs 2
"""

import jax
import jax.numpy as jnp

from common import bootstrap, cifar_loaders, sgd_steplr
from dtdl_tpu.ckpt import save_weights
from dtdl_tpu.metrics import Reporter, StdoutSink
from dtdl_tpu.models import pyramidnet
from dtdl_tpu.parallel import SingleDevice
from dtdl_tpu.train import evaluate, init_state, make_eval_step, \
    make_train_step, train_epoch
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_ckpt_flags, add_data_flags,
                                   add_train_flags, flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: single-device CIFAR-10 PyramidNet")
    add_train_flags(parser, batch_size=64, lr=0.1, epochs=20)
    add_data_flags(parser, dataset="cifar10")
    add_ckpt_flags(parser)
    flag(parser, "--gpu-nums", type=int, default=1,
         help="accepted for parity with the reference; must be 1 here")
    flag(parser, "--dtype", default="bfloat16",
         choices=["float32", "bfloat16"])
    args = parser.parse_args()
    if args.gpu_nums != 1:
        # reference guard: single_gpu.py:44-45 refuses gpu_nums != 1
        raise SystemExit("single_device.py trains on exactly one device; "
                         "use data_parallel.py / distributed_data_parallel.py")

    bootstrap(args)
    key = seed_everything(args.seed)
    strategy = SingleDevice()
    train_loader, val_loader = cifar_loaders(args, args.seed)
    tx, _ = sgd_steplr(args.lr, args.momentum, args.weight_decay,
                       len(train_loader))
    model = pyramidnet(dtype=jnp.dtype(args.dtype))
    state = init_state(model, key, jnp.zeros((1, 32, 32, 3)), tx)
    state = strategy.replicate(state)

    step = make_train_step(strategy)
    eval_step = make_eval_step(strategy)
    reporter = Reporter([StdoutSink()])
    for epoch in range(args.epochs):
        state, _ = train_epoch(step, state, train_loader, strategy,
                               reporter=reporter, epoch=epoch,
                               log_interval=args.log_interval)
        evaluate(eval_step, state, val_loader, strategy,
                 reporter=reporter, epoch=epoch)
    if args.save_model:
        path = save_weights(f"{args.out}/pyramidnet_final.msgpack",
                            state.params)
        print(f"saved weights to {path}", flush=True)


if __name__ == "__main__":
    main()
