"""Shared plumbing for the example scripts.

The reference duplicates its CLI/data/model blocks in every script (SURVEY
§2.4 notes the three identical TF2 Net/DataSet copies); the examples here
factor that into one module and keep each script focused on the distributed
idiom it demonstrates.  Flag names mirror the reference scripts, both
spellings accepted (dtdl_tpu.utils.config).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax

from dtdl_tpu.data import (
    CIFAR10_MEAN, CIFAR10_STD, DataLoader, ShardedSampler,
    cifar10_train_transform, load_dataset, normalize_transform,
)
from dtdl_tpu.runtime import initialize, is_leader
from dtdl_tpu.runtime.topology import banner
from dtdl_tpu.utils.config import parse_mesh_shape


def bootstrap(args):
    """Rendezvous (if multi-process) and print the leader banner.

    ``--platform cpu --fake-devices 8`` switches to a virtual CPU mesh via
    jax.config — env vars are too late here because this environment's
    sitecustomize initializes the TPU backend at interpreter start.
    """
    if getattr(args, "platform", ""):
        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu" and getattr(args, "fake_devices", 0):
            jax.config.update("jax_num_cpu_devices", args.fake_devices)
    topo = {"coordinator": getattr(args, "coordinator", ""),
            "num_processes": getattr(args, "num_processes", 1),
            "process_id": getattr(args, "process_id", 0)}
    if not topo["coordinator"]:
        # inside a multi-task SLURM allocation every script is launchable
        # with zero flags (the reference only advertised this; README.md:11)
        from dtdl_tpu.launch.slurm import maybe_slurm
        topo = maybe_slurm() or topo
    initialize(**topo)
    if is_leader():
        print(banner(), flush=True)


def build_mesh_from_args(args):
    from dtdl_tpu.runtime import build_mesh
    spec = parse_mesh_shape(args)
    if spec is None:
        return build_mesh()
    shape, axes = spec
    return build_mesh(shape, axes)


def _host_batch_and_sampler(n_examples: int, global_batch: int, *,
                            shuffle: bool, seed: int):
    """(per-host batch, this host's ShardedSampler) — the one place the
    global-batch split and dataset partition are decided."""
    nproc = jax.process_count()
    if global_batch % nproc:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{nproc} processes")
    sampler = ShardedSampler(n_examples, nproc, jax.process_index(),
                             shuffle=shuffle, seed=seed)
    return global_batch // nproc, sampler


def per_process_loader(images, labels, global_batch: int, *, shuffle: bool,
                       seed: int, transform=None, drop_last: bool = True):
    """Loader feeding this host's stripe of the global batch."""
    batch, sampler = _host_batch_and_sampler(
        len(labels), global_batch, shuffle=shuffle, seed=seed)
    return DataLoader({"image": images, "label": labels}, batch,
                      sampler=sampler, drop_last=drop_last,
                      transform=transform)


def _limit(args, train, test):
    (xtr, ytr), (xte, yte) = train, test
    for name in ("limit_train", "limit_test"):
        if getattr(args, name, 0) < 0:
            raise ValueError(f"--{name.replace('_', '-')} must be >= 0")
    if getattr(args, "limit_train", 0):
        xtr, ytr = xtr[: args.limit_train], ytr[: args.limit_train]
    if getattr(args, "limit_test", 0):
        xte, yte = xte[: args.limit_test], yte[: args.limit_test]
    return (xtr, ytr), (xte, yte)


def cifar_loaders(args, seed: int):
    """CIFAR-10 train/val loaders with the reference's augmentation
    (RandomCrop(32, pad 4) + flip + normalize, reference
    pytorch/single_gpu.py:51-55).

    ``--num-workers N`` (N > 0) routes the train pipeline through the native
    C++ producer/consumer loader — augment/normalize/batch on N worker
    threads, the role torch DataLoader's ``num_workers=4`` processes play
    for the reference (pytorch/single_gpu.py:21,60-61).  Both paths use the
    same ShardedSampler (per-host stripe of a per-epoch global
    permutation), so the loader backend never changes which examples a host
    trains on or the cross-host mixing semantics.
    """
    (xtr, ytr), (xte, yte) = _limit(
        args, *load_dataset("cifar10", args.dataset_dir,
                            download=getattr(args, "download", True)))
    workers = getattr(args, "num_workers", 0)
    if workers > 0:
        from dtdl_tpu.data.native_loader import NativeDataLoader
        batch, sampler = _host_batch_and_sampler(
            len(ytr), args.batch_size, shuffle=True, seed=seed)
        train = NativeDataLoader.or_python(
            xtr, ytr, batch, seed=seed, augment=True,
            mean=CIFAR10_MEAN, std=CIFAR10_STD, n_threads=workers,
            sampler=sampler)
        if jax.process_index() == 0:
            print(f"train loader: {type(train).__name__} "
                  f"({workers} workers)", flush=True)
    else:
        train = per_process_loader(
            xtr, ytr, args.batch_size, shuffle=True, seed=seed,
            transform=cifar10_train_transform(CIFAR10_MEAN, CIFAR10_STD))
    val = per_process_loader(
        xte, yte, args.batch_size, shuffle=False, seed=seed,
        transform=normalize_transform(CIFAR10_MEAN, CIFAR10_STD),
        drop_last=False)
    return train, val


def mnist_arrays(args, flatten: bool = False):
    return _limit(args, *load_dataset("mnist", args.dataset_dir,
                                      flatten=flatten))


def sgd_steplr(lr: float, momentum: float, weight_decay: float,
               steps_per_epoch: int, step_epochs: int = 2,
               gamma: float = 0.1):
    """SGD + StepLR(step=2 epochs, gamma=0.1) — the reference DDP optimizer
    (reference pytorch/distributed_data_parallel.py:94-97)."""
    schedule = optax.exponential_decay(
        lr, transition_steps=step_epochs * steps_per_epoch,
        decay_rate=gamma, staircase=True)
    tx = optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(schedule, momentum=momentum),
    )
    return tx, schedule
