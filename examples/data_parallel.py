#!/usr/bin/env python
"""Single-process multi-device data parallelism on CIFAR-10 PyramidNet.

Capability parity with reference pytorch/data_parallel.py: one process
driving all local devices.  Where ``nn.DataParallel`` replicates the module
and scatter/gathers every batch through device 0 (the 80%-GPU-util
bottleneck in the reference's own benchmark, pytorch/README.md:62-64), the
TPU version is SPMD over a local mesh: params live replicated on every chip,
each chip takes its batch shard, gradients pmean over ICI — no central
scatter/gather device.  Also fixes the reference's bug of ignoring
--dataset-dir (data_parallel.py:61 hardcodes /home/zhaopp5).

    python examples/data_parallel.py --gpu-nums 4 --batch-size 256
"""

import jax
import jax.numpy as jnp

from common import bootstrap, cifar_loaders, sgd_steplr
from dtdl_tpu.ckpt import save_weights
from dtdl_tpu.metrics import Reporter, StdoutSink
from dtdl_tpu.models import pyramidnet
from dtdl_tpu.parallel import DataParallel
from dtdl_tpu.runtime.mesh import build_mesh
from dtdl_tpu.train import evaluate, init_state, make_eval_step, \
    make_train_step, train_epoch
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_ckpt_flags, add_data_flags,
                                   add_train_flags, flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: single-process multi-device DP CIFAR-10")
    add_train_flags(parser, batch_size=64, lr=0.1, epochs=20)
    add_data_flags(parser, dataset="cifar10")
    add_ckpt_flags(parser)
    flag(parser, "--gpu-nums", "--device-nums", type=int, default=0,
         help="devices to use (0 = all local devices); the reference sets "
              "CUDA_VISIBLE_DEVICES instead (data_parallel.py:47-52)")
    flag(parser, "--dtype", default="bfloat16",
         choices=["float32", "bfloat16"])
    args = parser.parse_args()

    bootstrap(args)
    key = seed_everything(args.seed)
    devices = jax.local_devices()
    if args.gpu_nums:
        devices = devices[: args.gpu_nums]
    strategy = DataParallel(build_mesh(devices=devices))
    print(f"DataParallel over {strategy.num_replicas} local device(s); "
          f"global batch {args.batch_size} -> "
          f"{strategy.per_replica_batch(args.batch_size)}/replica", flush=True)

    train_loader, val_loader = cifar_loaders(args, args.seed)
    tx, _ = sgd_steplr(args.lr, args.momentum, args.weight_decay,
                       len(train_loader))
    model = pyramidnet(dtype=jnp.dtype(args.dtype))
    state = strategy.replicate(
        init_state(model, key, jnp.zeros((1, 32, 32, 3)), tx))

    step = make_train_step(strategy)
    eval_step = make_eval_step(strategy)
    reporter = Reporter([StdoutSink()])
    for epoch in range(args.epochs):
        state, _ = train_epoch(step, state, train_loader, strategy,
                               reporter=reporter, epoch=epoch,
                               log_interval=args.log_interval)
        evaluate(eval_step, state, val_loader, strategy,
                 reporter=reporter, epoch=epoch)
    if args.save_model:
        path = save_weights(f"{args.out}/pyramidnet_final.msgpack",
                            state.params)
        print(f"saved weights to {path}", flush=True)


if __name__ == "__main__":
    main()
