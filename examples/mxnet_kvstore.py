#!/usr/bin/env python
"""MNIST CNN via the MXNet idiom: Module.fit over a KVStore.

The reference's ``mxnet/`` track is declared (reference README.md:4-20) but
empty (``mxnet/README.md`` is zero-byte, SURVEY §2.1).  MXNet's canonical
distributed-training shape — the one its own image-classification examples
use — is::

    kv  = mx.kv.create(args.kv_store)            # 'local'|'device'|'dist_sync'
    mod = mx.mod.Module(symbol, context=ctxs)
    mod.fit(train_iter, eval_data=val_iter, optimizer='sgd',
            optimizer_params={'learning_rate': .1}, kvstore=kv,
            batch_end_callback=mx.callback.Speedometer(batch, 100),
            num_epoch=10)

This script is that surface rebuilt TPU-native: the KVStore aggregates
gradients with an XLA AllReduce over the mesh's data axis instead of a
parameter-server tier (dtdl_tpu/parallel/kvstore.py), and Module.fit drives
the jitted train-step engine.  ``--kv-store dist_async`` is accepted and
routed to synchronous aggregation (see kvstore.py docstring).

    python examples/mxnet_kvstore.py --kv-store device --batch-size 64
    python examples/mxnet_kvstore.py --kv-store dist_sync \
        --coordinator host:1234 --num-processes 2 --process-id 0
"""

import time

import jax.numpy as jnp
import numpy as np
import optax

from common import bootstrap, mnist_arrays, per_process_loader
from dtdl_tpu.data.loader import prefetch_to_device
from dtdl_tpu.metrics.report import Accumulator
from dtdl_tpu.models import MnistCNN
from dtdl_tpu.parallel.kvstore import create as kv_create, kvstore_strategy
from dtdl_tpu.train import init_state, make_eval_step, make_train_step
from dtdl_tpu.train.loop import evaluate
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_ckpt_flags, add_data_flags,
                                   add_topology_flags, flag, make_parser)


class Speedometer:
    """MXNet's batch_end_callback: periodic samples/sec + metric line.

    Resets its window at every epoch boundary (like MXNet's) so validation
    and epoch-summary time never pollute a measurement window.
    """

    def __init__(self, batch_size: int, frequent: int = 50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._epoch = -1

    def __call__(self, epoch: int, nbatch: int, metrics: dict) -> None:
        if epoch != self._epoch:
            self._epoch = epoch
            self.tic = time.time()
            self.count = 0
        self.count += 1
        if self.count % self.frequent:
            return
        speed = self.frequent * self.batch_size / (time.time() - self.tic)
        line = "\t".join(f"{k}={v:.6f}" for k, v in metrics.items())
        print(f"Epoch[{epoch}] Batch [{nbatch}]\tSpeed: {speed:.2f} "
              f"samples/sec\t{line}", flush=True)
        self.tic = time.time()


class Module:
    """MXNet Module-flavored wrapper: symbol + context → fit()."""

    def __init__(self, symbol, strategy):
        self.symbol = symbol
        self.strategy = strategy
        self.state = None

    def fit(self, train_loader, eval_loader=None, optimizer="sgd",
            optimizer_params=None, num_epoch: int = 10,
            batch_end_callback=None, seed: int = 0):
        params = dict(optimizer_params or {})
        lr = params.pop("learning_rate", 0.01)
        momentum = params.pop("momentum", 0.0)
        wd = params.pop("wd", 0.0)
        if optimizer == "sgd":
            tx = optax.chain(optax.add_decayed_weights(wd),
                             optax.sgd(lr, momentum=momentum or None))
        elif optimizer == "adam":
            tx = optax.adam(lr)
        else:
            raise ValueError(f"unsupported optimizer {optimizer!r}")

        key = seed_everything(seed)
        self.state = self.strategy.replicate(init_state(
            self.symbol, key, jnp.zeros((1, 28, 28, 1)), tx))
        train_step = make_train_step(self.strategy)
        eval_step = make_eval_step(self.strategy)

        for epoch in range(num_epoch):
            train_loader.set_epoch(epoch)
            acc = Accumulator()
            tic = time.time()
            it = prefetch_to_device(iter(train_loader),
                                    self.strategy.shard_batch)
            for nbatch, batch in enumerate(it):
                self.state, metrics = train_step(self.state, batch)
                metrics = {k: float(v) for k, v in metrics.items()}
                acc.add(metrics)
                if batch_end_callback is not None:
                    batch_end_callback(epoch, nbatch, metrics)
            means = acc.means()
            print(f"Epoch[{epoch}] Train-accuracy={means['accuracy']:.6f}")
            print(f"Epoch[{epoch}] Time cost={time.time() - tic:.3f}")
            if eval_loader is not None:
                val = evaluate(eval_step, self.state, eval_loader,
                               self.strategy)
                print(f"Epoch[{epoch}] Validation-accuracy="
                      f"{val['accuracy']:.6f}", flush=True)
        return self.state


def main():
    parser = make_parser("dtdl_tpu: MXNet-style Module.fit over a KVStore")
    flag(parser, "--kv-store", type=str, default="device",
         choices=["local", "device", "dist_sync", "dist_device_sync",
                  "dist_async"])
    flag(parser, "-b", "--batch-size", type=int, default=64,
         help="GLOBAL batch size")
    flag(parser, "--lr", type=float, default=0.05)
    flag(parser, "--momentum", type=float, default=0.9)
    flag(parser, "--num-epochs", "--epochs", type=int, default=3)
    flag(parser, "--disp-batches", type=int, default=50,
         help="Speedometer frequency")
    flag(parser, "--seed", type=int, default=0)
    add_data_flags(parser, dataset="mnist")
    add_ckpt_flags(parser)
    add_topology_flags(parser)
    args = parser.parse_args()
    bootstrap(args)

    kv = kv_create(args.kv_store)
    strategy = kvstore_strategy(kv)
    print(f"kvstore: kind={kv.kind} rank={kv.rank} "
          f"num_workers={kv.num_workers} width={kv.aggregation_width}",
          flush=True)

    (x, y), (vx, vy) = mnist_arrays(args)
    train_loader = per_process_loader(x, y, args.batch_size, shuffle=True,
                                      seed=args.seed)
    val_loader = per_process_loader(vx, vy, args.batch_size, shuffle=False,
                                    seed=args.seed, drop_last=False)

    mod = Module(MnistCNN(), strategy)
    state = mod.fit(train_loader, eval_loader=val_loader, optimizer="sgd",
                    optimizer_params={"learning_rate": args.lr,
                                      "momentum": args.momentum},
                    num_epoch=args.num_epochs,
                    batch_end_callback=Speedometer(args.batch_size,
                                                   args.disp_batches),
                    seed=args.seed)

    if args.save_model:
        # leader-gating + cross-host barrier live inside save_weights
        from dtdl_tpu.ckpt import save_weights
        save_weights(f"{args.out}/mxnet_cnn.msgpack", state.params)


if __name__ == "__main__":
    main()
