#!/usr/bin/env python
"""MNIST CNN with the Keras-style compile/fit API, single process.

Capability parity with reference tensorflow2/mnist_single.py: build the
3-conv CNN, ``fit`` with TensorBoard + per-epoch checkpoint callbacks,
then restore the latest checkpoint and evaluate (reference :65-92).
Flag names match the reference's argparse block (:97-115).

    python examples/mnist_single.py --batch_size 64 --epochs 2
"""

import jax.numpy as jnp
import optax

from common import bootstrap, mnist_arrays
from dtdl_tpu.models import MnistCNN
from dtdl_tpu.parallel import SingleDevice
from dtdl_tpu.train import Model, ModelCheckpoint, TensorBoard
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import add_data_flags, flag, make_parser


def add_tf2_flags(parser):
    """The reference's flag surface (tensorflow2/mnist_single.py:97-115)."""
    flag(parser, "--train_dir", "-td", type=str, default="./train_dir")
    flag(parser, "--batch_size", "-b", type=int, default=64)
    flag(parser, "--test_batchsize", "-tb", type=int, default=1000)
    flag(parser, "--epochs", "-e", type=int, default=10)
    flag(parser, "--gpu_nums", "-g", type=int, default=0)
    flag(parser, "--cpu_nums", "-c", type=int, default=0)
    flag(parser, "--learning_rate", "-lr", type=float, default=0.01)
    flag(parser, "--momentum", type=float, default=0.5)
    flag(parser, "--log_interval", type=int, default=10)
    flag(parser, "--save_model", "-sm", action="store_true", default=False)
    flag(parser, "--seed", type=int, default=0)


def run(args, strategy):
    seed_everything(args.seed)
    (x, y), (vx, vy) = mnist_arrays(args)
    model = Model(MnistCNN(dtype=jnp.bfloat16), strategy)
    model.compile(
        optimizer=optax.sgd(args.learning_rate, momentum=args.momentum),
        loss="sparse_categorical_crossentropy", seed=args.seed)
    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              validation_data=(vx, vy),
              callbacks=[ModelCheckpoint(args.train_dir),
                         TensorBoard(f"{args.train_dir}/logs")])
    # EVAL after restore-latest (reference tensorflow2/mnist_single.py:88-92)
    model.load_latest(args.train_dir)
    res = model.evaluate(vx, vy, batch_size=args.test_batchsize)
    print(f"Eval loss: {res['loss']}, Eval Accuracy: {res['accuracy']}",
          flush=True)
    if args.save_model:
        model.save_weights(f"{args.train_dir}/final.msgpack")


def main():
    parser = make_parser("dtdl_tpu: Keras-style MNIST CNN (single)")
    add_tf2_flags(parser)
    add_data_flags(parser, dataset="mnist")
    args = parser.parse_args()
    bootstrap(args)
    run(args, SingleDevice())


if __name__ == "__main__":
    main()
