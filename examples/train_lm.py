#!/usr/bin/env python
"""Causal language-model training (data-parallel) — beyond-reference capability.

The reference's largest model is a CNN over 32x32 images (SURVEY §5.7: no
sequence models anywhere); this example shows the framework's long-context
side on the same engine the image examples use: TransformerLM with the Pallas
flash-attention kernel, next-token loss, DP/DDP via the strategy layer, and
the standard checkpoint/metrics plumbing.

    python examples/train_lm.py --batch-size 32 --seq-len 128 --epochs 2
    python examples/train_lm.py --strategy ddp --coordinator h0:9999 \
        --num-processes 2 --process-id 0        # multi-host DDP
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from common import bootstrap
from dtdl_tpu.ckpt import save_weights
from dtdl_tpu.data import DataLoader, ShardedSampler, load_dataset
from dtdl_tpu.metrics import Reporter, StdoutSink
from dtdl_tpu.models import transformer_lm
from dtdl_tpu.obs import (GoodputMeter, Observer, lm_train_flops,
                          peak_flops_per_chip)
from dtdl_tpu.parallel import choose_strategy
from dtdl_tpu.train import init_state, make_lm_train_step
from dtdl_tpu.utils import seed_everything
from dtdl_tpu.utils.config import (add_ckpt_flags, add_data_flags,
                                   add_topology_flags, add_train_flags,
                                   flag, make_parser)


def main():
    parser = make_parser("dtdl_tpu: causal LM training (DP/DDP)")
    add_train_flags(parser, batch_size=32, lr=3e-4, epochs=2)
    add_data_flags(parser, dataset="synthetic_lm")
    add_ckpt_flags(parser)
    add_topology_flags(parser)
    flag(parser, "--strategy", default="auto",
         choices=["auto", "single", "dp", "ddp"])
    flag(parser, "--model-size", default="tiny",
         choices=["tiny", "small", "base"])
    flag(parser, "--seq-len", type=int, default=128)
    flag(parser, "--attn", default="flash", choices=["flash", "dense"])
    flag(parser, "--vocab-chunk-size", type=int, default=0,
         help=">0: vocab-chunked LM loss with tiles of N vocab COLUMNS "
              "(e.g. 2048) — the [B,S,V] logits are never materialized, "
              "so large-vocab models fit at long sequence")
    flag(parser, "--n-experts", type=int, default=0,
         help=">0: switch-MoE MLPs with this many experts")
    flag(parser, "--moe-dispatch", default="dense",
         choices=["dense", "routed"],
         help="MoE dispatch: dense one-hot oracle, or GShard-style "
              "capacity-factor top-k (the scale path — same flag surface "
              "as train_lm_4d.py)")
    flag(parser, "--capacity-factor", type=float, default=1.25,
         help="routed: per-expert slots = ceil(cf * seq * k / n_experts)")
    flag(parser, "--moe-top-k", type=int, default=1,
         help="routed: experts per token (1 = Switch, 2 = GShard top-2)")
    flag(parser, "--moe-group-size", type=int, default=0,
         help="routed: routing-group token cap (0 = 1024, the measured "
              "knee; capacity applies per group)")
    flag(parser, "--moe-aux-weight", type=float, default=0.01,
         help="Switch load-balance aux loss weight (added to the "
              "training loss; 0 disables)")
    flag(parser, "--generate-tokens", type=int, default=0,
         help=">0: after training, greedily decode this many tokens from "
              "a training-prefix prompt (KV-cache generate) and print "
              "them — an end-to-end check of the inference path")
    flag(parser, "--trace", default="",
         help="write a Chrome-trace-event JSON (Perfetto-loadable) of "
              "the host phases + settled device windows to this path")
    args = parser.parse_args()

    if args.dataset != "synthetic_lm":
        raise SystemExit("train_lm.py trains on token data; "
                         "use --dataset synthetic_lm")

    bootstrap(args)
    key = seed_everything(args.seed)
    strategy = choose_strategy(args.strategy)

    train_tokens, _ = load_dataset(args.dataset, seq_len=args.seq_len)
    model = transformer_lm(args.model_size, max_seq=args.seq_len,
                           attn_impl=args.attn, n_experts=args.n_experts,
                           moe_dispatch=args.moe_dispatch,
                           capacity_factor=args.capacity_factor,
                           moe_top_k=args.moe_top_k,
                           moe_group_size=args.moe_group_size)
    if train_tokens.max() >= model.vocab_size:
        raise SystemExit("dataset vocab exceeds model vocab")

    nproc = jax.process_count()
    strategy.per_replica_batch(args.batch_size)   # validate divisibility
    sampler = ShardedSampler(len(train_tokens), nproc, jax.process_index(),
                             shuffle=True, seed=args.seed)
    loader = DataLoader({"tokens": train_tokens}, args.batch_size // nproc,
                        sampler=sampler)

    state = init_state(model, key,
                       jnp.zeros((1, args.seq_len), jnp.int32),
                       optax.adamw(args.lr))
    state = strategy.replicate(state)
    step = make_lm_train_step(strategy,
                              vocab_chunk_size=args.vocab_chunk_size,
                              moe_aux_weight=args.moe_aux_weight)

    # observability (dtdl_tpu.obs): goodput/MFU per log window through the
    # reporter, a recompile sentinel on the step, and — with --trace — a
    # Perfetto-loadable span trace of the host phases
    per_host_bs = args.batch_size // nproc
    # flops_per_step covers the whole per-host step (sharded over all
    # local devices), so the peak must be per-host too — per-chip peak
    # times local chips, matching bench.py's per-device convention
    peak = peak_flops_per_chip()
    obs = Observer(trace_path=args.trace or None, sentinel="warn",
                   goodput=GoodputMeter(
                       flops_per_step=lm_train_flops(model, per_host_bs,
                                                     args.seq_len),
                       tokens_per_step=per_host_bs * (args.seq_len - 1),
                       peak_flops=peak * jax.local_device_count()
                       if peak else None))
    step = obs.watch(step, "lm_train_step")
    global_step = 0
    import time as _time
    t_win, steps_win = _time.perf_counter(), 0
    with Reporter([StdoutSink()]) as reporter:
        for epoch in range(args.epochs):
            loader.set_epoch(epoch)
            for batch in loader:
                with obs.span("data"):
                    sharded = strategy.shard_batch(
                        {"tokens": jnp.asarray(batch["tokens"])})
                with obs.span("dispatch", step=global_step):
                    state, metrics = step(state, sharded)
                steps_win += 1
                if global_step % args.log_interval == 0:
                    with obs.span("drain"):
                        row = {"epoch": epoch, "step": global_step,
                               "loss": float(metrics["loss"]),
                               "accuracy": float(metrics["accuracy"]),
                               "ppl": float(np.exp(
                                   min(20.0, float(metrics["loss"]))))}
                        if "moe_aux_loss" in metrics:
                            row["moe_aux_loss"] = float(
                                metrics["moe_aux_loss"])
                    # the float() above settled the window: honest goodput
                    row.update(obs.window(steps_win,
                                          _time.perf_counter() - t_win))
                    t_win, steps_win = _time.perf_counter(), 0
                    reporter.report(row)
                global_step += 1
    if args.trace:
        print(f"trace written to {obs.save()}", flush=True)
    if args.save_model:
        path = save_weights(f"{args.out}/lm_final.msgpack", state.params)
        print(f"saved weights to {path}", flush=True)
    # diagnostic decode runs AFTER the save: a generation error (bad
    # flag combination, OOM) must never discard the trained weights
    if args.generate_tokens:
        from dtdl_tpu.models import generate
        if jax.process_count() == 1:
            # one prompt row per replica: the decode itself runs under
            # the training strategy (batch-sharded caches), like training
            n_rows = max(1, strategy.num_replicas)
            prompt = jnp.asarray(train_tokens[:n_rows, :8], jnp.int32)
            out = generate(model, state.params, prompt,
                           max_new_tokens=args.generate_tokens,
                           strategy=strategy)
        else:
            # multi-host: shard_batch would treat the prompt as this
            # host's contribution to a process-spanning global array
            # (batch x process_count vs the compiled cache shapes, and a
            # non-addressable output) — decode host-locally instead
            prompt = jnp.asarray(train_tokens[:1, :8], jnp.int32)
            out = generate(model, jax.device_get(state.params), prompt,
                           max_new_tokens=args.generate_tokens)
        print("generated:", np.asarray(out)[0].tolist(), flush=True)


if __name__ == "__main__":
    main()
